"""Serving stack end-to-end: InferenceModel, ClusterServing over loopback,
client queues, error paths, backpressure, and the HTTP frontend.

Reference test strategy (SURVEY.md §4.3): serving pre/post-processing and
engine specs ran on a Flink MiniCluster + local Redis.  The analog here is
the real server on a loopback port with real sockets and threads.
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import analytics_zoo_tpu.nn as nn
from analytics_zoo_tpu.core import init_orca_context
from analytics_zoo_tpu.serving import (ClusterServing, HTTPFrontend,
                                       InferenceModel, InputQueue,
                                       OutputQueue)
from analytics_zoo_tpu.serving import protocol


def _linear_model():
    init_orca_context("local")

    class M(nn.Module):
        def forward(self, scope, x):
            return scope.child(nn.Dense(3), x, name="fc")

    m = M()
    variables = m.init(__import__("jax").random.PRNGKey(0),
                       np.zeros((1, 4), np.float32))
    return m, variables


@pytest.fixture(scope="module")
def inference_model():
    m, variables = _linear_model()
    return InferenceModel(batch_buckets=(1, 4, 8)).load(m, variables)


# -- InferenceModel alone -----------------------------------------------------

def test_inference_model_bucket_padding(inference_model):
    x = np.random.default_rng(0).normal(size=(3, 4)).astype(np.float32)
    out = inference_model.predict(x)
    assert out.shape == (3, 3)
    # per-row result must not depend on bucket padding
    row0 = inference_model.predict(x[:1])
    np.testing.assert_allclose(out[0], row0[0], rtol=1e-5)


def test_inference_model_chunking(inference_model):
    x = np.random.default_rng(1).normal(size=(19, 4)).astype(np.float32)
    out = inference_model.predict(x)          # 19 > largest bucket (8)
    assert out.shape == (19, 3)
    np.testing.assert_allclose(out[:4], inference_model.predict(x[:4]),
                               rtol=1e-5)


# -- ClusterServing round-trips ----------------------------------------------

def test_serving_round_trip(inference_model):
    with ClusterServing(inference_model, batch_size=4) as srv:
        iq = InputQueue(srv.host, srv.port)
        oq = OutputQueue(input_queue=iq)
        x = np.arange(4, dtype=np.float32)
        uid = iq.enqueue("t", t=x)
        out = oq.query(uid, timeout=20.0)
        assert out is not None and out.shape == (3,)
        expect = inference_model.predict(x[None])[0]
        np.testing.assert_allclose(out, expect, rtol=1e-5)


def test_serving_concurrent_mixed_shapes(inference_model):
    """Many clients, two different feature shapes, all answered correctly."""
    with ClusterServing(inference_model, batch_size=8,
                        batch_timeout_ms=20) as srv:
        results = {}
        errors = []

        def client(i):
            try:
                iq = InputQueue(srv.host, srv.port)
                oq = OutputQueue(input_queue=iq)
                x = np.full((4,), float(i), np.float32)
                uid = iq.enqueue(f"c{i}", t=x)
                out = oq.query(uid, timeout=30.0)
                results[i] = out
            except Exception as e:  # noqa: BLE001
                errors.append((i, e))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        assert len(results) == 12
        for i, out in results.items():
            expect = inference_model.predict(
                np.full((1, 4), float(i), np.float32))[0]
            np.testing.assert_allclose(out, expect, rtol=1e-5)


def test_serving_survives_header_only_frame(inference_model):
    """ADVICE r1 (high): a header-only frame must get an error reply and must
    NOT kill the batcher thread for everyone else."""
    import socket
    with ClusterServing(inference_model, batch_size=2) as srv:
        raw = socket.create_connection((srv.host, srv.port), timeout=10)
        try:
            protocol.send_frame(raw, protocol.encode({"uuid": "bad-1"}))
            reply = protocol.recv_frame(raw)
            header, arr = protocol.decode(reply)
            assert header["uuid"] == "bad-1" and "error" in header
        finally:
            raw.close()
        # the server must still answer a valid request afterwards
        iq = InputQueue(srv.host, srv.port)
        oq = OutputQueue(input_queue=iq)
        uid = iq.enqueue("ok", t=np.ones(4, np.float32))
        assert oq.query(uid, timeout=20.0) is not None


class _SlowModel:
    """Stub standing in for InferenceModel: slow + optionally failing."""

    def __init__(self, delay=0.0, fail=False):
        self.delay = delay
        self.fail = fail

    def predict(self, x):
        if self.delay:
            time.sleep(self.delay)
        if self.fail:
            raise ValueError("boom")
        return np.asarray(x) * 2.0


def test_serving_error_reply_reaches_client():
    with ClusterServing(_SlowModel(fail=True), batch_size=2) as srv:
        iq = InputQueue(srv.host, srv.port)
        oq = OutputQueue(input_queue=iq)
        uid = iq.enqueue("t", t=np.ones(4, np.float32))
        with pytest.raises(RuntimeError, match="boom"):
            oq.query(uid, timeout=20.0)
        # batcher survives a failing model too
        uid2 = iq.enqueue("t2", t=np.ones(4, np.float32))
        with pytest.raises(RuntimeError, match="boom"):
            oq.query(uid2, timeout=20.0)


def test_serving_backpressure_queue_full():
    """With a 1-slot queue, a slow model, and a tiny push timeout, floods get
    explicit 'queue full' error replies instead of silent drops.  Retries
    are disabled so the raw server-side rejection reaches the caller
    (the default client retries these — tests/test_robustness.py)."""
    from analytics_zoo_tpu.serving.client import RetryPolicy
    with ClusterServing(_SlowModel(delay=0.3), batch_size=1,
                        queue_items=1, push_timeout=0.05) as srv:
        iq = InputQueue(srv.host, srv.port,
                        retry=RetryPolicy(max_attempts=1))
        oq = OutputQueue(input_queue=iq)
        uids = [iq.enqueue(f"f{i}", t=np.ones(2, np.float32))
                for i in range(8)]
        outcomes = {"ok": 0, "full": 0}
        for uid in uids:
            try:
                out = oq.query(uid, timeout=30.0)
                if out is not None:
                    outcomes["ok"] += 1
            except RuntimeError as e:
                assert "queue full" in str(e)
                outcomes["full"] += 1
        assert outcomes["ok"] >= 1     # service still makes progress
        assert outcomes["full"] >= 1   # and sheds load explicitly


def test_native_queue_empty_payload():
    """ADVICE r1 (low): a zero-length payload is a valid item, not a
    timeout."""
    from analytics_zoo_tpu.native import NativeQueue
    q = NativeQueue(max_items=4)
    assert q.push(b"", tag=7)
    item = q.pop(timeout=1.0)
    assert item is not None
    payload, tag = item
    assert payload == b"" and tag == 7


# -- HTTP frontend ------------------------------------------------------------

def test_http_frontend(inference_model):
    with ClusterServing(inference_model, batch_size=4) as srv:
        with HTTPFrontend(srv.host, srv.port) as fe:
            url = f"http://{fe.host}:{fe.port}"
            with urllib.request.urlopen(url + "/health", timeout=10) as r:
                assert json.load(r)["status"] == "ok"
            req = urllib.request.Request(
                url + "/predict",
                data=json.dumps({"instances": [[1, 2, 3, 4]]}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                pred = json.load(r)["predictions"]
            expect = inference_model.predict(
                np.asarray([[1, 2, 3, 4]], np.float32))
            np.testing.assert_allclose(np.asarray(pred), expect, rtol=1e-4)


def test_http_frontend_bad_request(inference_model):
    with ClusterServing(inference_model, batch_size=4) as srv:
        with HTTPFrontend(srv.host, srv.port) as fe:
            url = f"http://{fe.host}:{fe.port}/predict"
            req = urllib.request.Request(
                url, data=b'{"wrong": 1}',
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=10)
            assert ei.value.code == 400


def test_http_frontend_reconnects_after_backend_restart(inference_model):
    """A backend restart must not permanently kill the HTTP frontend."""
    srv = ClusterServing(inference_model, batch_size=4).start()
    port = srv.port
    fe = HTTPFrontend(srv.host, port).start()
    try:
        x = np.ones((1, 4), np.float32)
        assert fe.predict(x) is not None
        srv.stop()
        deadline = time.time() + 10
        while True:  # wait for the OS to release the port
            try:
                srv = ClusterServing(inference_model, port=port,
                                     batch_size=4).start()
                break
            except OSError:
                if time.time() > deadline:
                    raise
                time.sleep(0.1)
        out = fe.predict(x)  # reconnect happens inside predict
        assert out is not None
        np.testing.assert_allclose(np.squeeze(out),
                                   np.squeeze(inference_model.predict(x)),
                                   rtol=1e-5)
    finally:
        fe.stop()
        srv.stop()


def test_serving_and_frontend_stats(inference_model):
    with ClusterServing(inference_model, batch_size=4) as srv:
        with HTTPFrontend(srv.host, srv.port) as fe:
            url = f"http://{fe.host}:{fe.port}"
            for _ in range(3):
                req = urllib.request.Request(
                    url + "/predict",
                    data=json.dumps({"instances": [[1, 2, 3, 4]]}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=30):
                    pass
            with urllib.request.urlopen(url + "/stats", timeout=10) as r:
                fstats = json.load(r)
            assert fstats["requests"] == 3 and fstats["timeouts"] == 0
        s = srv.stats()
        assert s["requests"] == 3 and s["replies"] == 3
        assert s["batches"] >= 1 and s["errors"] == 0
        assert 1.0 <= s["mean_batch_size"] <= 4.0


def test_inference_model_bf16_serving_dtype():
    import jax
    import jax.numpy as jnp
    import analytics_zoo_tpu.nn as nn
    m = nn.Sequential([nn.Dense(8, activation="relu"), nn.Dense(3)])
    v = m.init(jax.random.PRNGKey(0), np.ones((1, 4), np.float32))
    f32 = InferenceModel().load(m, v)
    bf16 = InferenceModel().load(m, v, dtype=jnp.bfloat16)
    x = np.random.default_rng(0).normal(size=(4, 4)).astype(np.float32)
    a, b = f32.predict(x), bf16.predict(x)
    np.testing.assert_allclose(a, b, rtol=2e-2, atol=2e-2)  # bf16 tolerance
    assert not np.allclose(a, b, rtol=1e-7, atol=0)  # actually lower precision


def test_update_model_hot_swap():
    import jax
    import analytics_zoo_tpu.nn as nn

    def make(bias_val):
        m = nn.Sequential([nn.Lambda(lambda x: x * 0.0 + bias_val)])
        v = m.init(jax.random.PRNGKey(0), np.ones((1, 4), np.float32))
        return InferenceModel().load(m, v)

    with ClusterServing(make(1.0), batch_size=4) as srv:
        q = InputQueue(srv.host, srv.port)
        out_q = OutputQueue(input_queue=q)
        uid = q.enqueue("a", t=np.ones(4, np.float32))
        before = out_q.query(uid, timeout=30)
        np.testing.assert_allclose(before, np.ones(4), rtol=1e-6)
        srv.update_model(make(2.0))  # hot-swap on the SAME connection
        uid2 = q.enqueue("b", t=np.ones(4, np.float32))
        after = out_q.query(uid2, timeout=30)
        np.testing.assert_allclose(after, np.full(4, 2.0), rtol=1e-6)
        q.close()


def test_inference_model_int8_weight_quantization():
    """Weight-only int8 serving (reference: doLoadOpenVINOInt8): large
    float params are stored int8 + per-channel scales (4x smaller), and
    predictions stay close to the f32 model."""
    import jax
    import jax.numpy as jnp
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.serving.inference_model import (InferenceModel,
                                                           _Q_MARKER)

    init_orca_context("local")
    model = nn.Sequential([nn.Dense(256, activation="relu"),
                           nn.Dense(128, activation="relu"),
                           nn.Dense(10)])
    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 64)).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(x))

    ref = InferenceModel().load(model, variables)
    q = InferenceModel().load(model, variables, dtype="int8")
    out_ref = np.asarray(ref.predict(x), np.float32)
    out_q = np.asarray(q.predict(x), np.float32)
    # int8 weights + bf16 activations: small but nonzero error
    denom = np.maximum(np.abs(out_ref), 1.0)
    assert np.max(np.abs(out_q - out_ref) / denom) < 0.08

    # big kernels really stored int8; small leaves (biases) stay float
    p = q._variables["params"]
    layer0 = p[next(iter(p))]  # first Dense layer's params
    k0 = layer0["kernel"]
    assert isinstance(k0, dict) and _Q_MARKER in k0
    assert k0["q"].dtype == jnp.int8
    assert not isinstance(layer0["bias"], dict)


def test_inference_model_int8_calibrated_activations():
    """Calibrated int8 (reference: OpenVINO INT8 calibration): a
    calibration batch freezes static per-tensor activation scales; Dense
    matmuls then run int8 x int8 -> int32 with per-channel rescale.
    Accuracy must stay close to f32, and the activation scales must
    actually come from the calibration pass."""
    import jax
    import jax.numpy as jnp
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.serving.inference_model import InferenceModel

    init_orca_context("local")
    model = nn.Sequential([nn.Dense(256, activation="relu"),
                           nn.Dense(128, activation="relu"),
                           nn.Dense(10)])
    rng = np.random.default_rng(3)
    calib = rng.normal(size=(32, 64)).astype(np.float32)
    x = rng.normal(size=(16, 64)).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(calib))

    ref = InferenceModel().load(model, variables)
    q = InferenceModel().load(model, variables, dtype="int8",
                              calibrate=calib)
    # one scale per Dense layer, recorded during the calibration forward
    assert q._quant_ctx is not None and len(q._quant_ctx.amax) == 3
    assert all(a > 0 for a in q._quant_ctx.amax.values())
    out_ref = np.asarray(ref.predict(x), np.float32)
    out_q = np.asarray(q.predict(x), np.float32)
    # int8 weights AND int8 activations: bounded accuracy delta vs f32
    denom = np.maximum(np.abs(out_ref), 1.0)
    assert np.max(np.abs(out_q - out_ref) / denom) < 0.15
    # ranking (the serving-relevant signal) preserved on most rows
    agree = np.mean(out_q.argmax(1) == out_ref.argmax(1))
    assert agree >= 0.8


def test_inference_model_int8_calibrated_with_lstm():
    """Regression (r4 review): calibrated int8 must leave NON-Dense 2-D
    kernels (LSTM input/recurrent kernels) dequantized — only nn.Dense
    can consume the int8 dict form."""
    import jax
    import jax.numpy as jnp
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.serving.inference_model import InferenceModel

    init_orca_context("local")
    model = nn.Sequential([nn.LSTM(64), nn.Dense(16, activation="relu"),
                           nn.Dense(4)])
    rng = np.random.default_rng(5)
    calib = rng.normal(size=(8, 12, 16)).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(calib))
    ref = InferenceModel().load(model, variables)
    q = InferenceModel().load(model, variables, dtype="int8",
                              calibrate=calib)
    x = rng.normal(size=(4, 12, 16)).astype(np.float32)
    out_ref = np.asarray(ref.predict(x), np.float32)
    out_q = np.asarray(q.predict(x), np.float32)  # must not crash
    denom = np.maximum(np.abs(out_ref), 1.0)
    assert np.max(np.abs(out_q - out_ref) / denom) < 0.2


def test_inference_model_reload_and_int8_dtype_spellings():
    """Regression (r3 review): reloading clears stale executables, and
    jnp.int8/np.int8 route to weight-only quantization (NOT a float->int
    cast that zeroes weights)."""
    import jax
    import jax.numpy as jnp
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.serving import InferenceModel

    init_orca_context("local")
    model = nn.Sequential([nn.Dense(128, activation="relu"),
                           nn.Dense(4)])
    rng = np.random.default_rng(0)
    x = rng.normal(size=(4, 64)).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(x))

    im = InferenceModel()
    im.load(model, variables)
    ref = np.asarray(im.predict(x), np.float32)
    # reload with a different variable STRUCTURE (int8 markers) — must
    # recompile, not crash on the stale executable
    im.load(model, variables, dtype=jnp.int8)
    out = np.asarray(im.predict(x), np.float32)
    assert not np.allclose(out, 0.0)  # int8 CAST would zero the weights
    denom = np.maximum(np.abs(ref), 1.0)
    assert np.max(np.abs(out - ref) / denom) < 0.08


def test_calibrate_without_int8_raises():
    """Regression (r4 review): a calibration batch with a non-int8 dtype
    must error, not be silently ignored."""
    import jax
    import jax.numpy as jnp
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.serving.inference_model import InferenceModel
    init_orca_context("local")
    m = nn.Sequential([nn.Dense(4)])
    x = np.zeros((2, 3), np.float32)
    v = m.init(jax.random.PRNGKey(0), jnp.asarray(x))
    with pytest.raises(ValueError, match="calibrate"):
        InferenceModel().load(m, v, calibrate=x)
    with pytest.raises(ValueError, match="calibrate"):
        InferenceModel().load(m, v, dtype=jnp.bfloat16, calibrate=x)


def test_calibrator_rejects_traced_forward():
    """Regression (r4 advisor): running the calibration forward under
    jit must fail with an actionable message, not an opaque
    TracerError deep inside float()."""
    import jax
    import jax.numpy as jnp
    from analytics_zoo_tpu.nn.quant import Calibrator

    calib = Calibrator()

    def f(x):
        calib.observe(("dense",), x)
        return x

    with pytest.raises(RuntimeError, match="UNJITTED"):
        jax.jit(f)(jnp.ones((2, 2)))


def test_inference_model_int8_calibrated_conv():
    """Calibrated int8 for CNNs (reference: OpenVINO INT8 calibrated
    whole CNNs): plain Conv2D inputs get static activation scales and
    run as int8 x int8 -> int32 convs; accuracy stays bounded vs f32 and
    the conv kernels really stay int8 through the serving path."""
    import jax
    import jax.numpy as jnp
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.serving.inference_model import InferenceModel

    init_orca_context("local")
    model = nn.Sequential([
        nn.Conv2D(32, 3, activation="relu"),
        nn.Conv2D(64, 3, strides=2, activation="relu"),
        nn.GlobalAveragePooling2D(),
        nn.Dense(10)])
    rng = np.random.default_rng(7)
    calib = rng.normal(size=(16, 16, 16, 3)).astype(np.float32)
    x = rng.normal(size=(8, 16, 16, 3)).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(calib))

    ref = InferenceModel().load(model, variables)
    q = InferenceModel().load(model, variables, dtype="int8",
                              calibrate=calib)
    # both convs AND the dense observed during calibration
    assert q._quant_ctx is not None and len(q._quant_ctx.amax) == 3
    out_ref = np.asarray(ref.predict(x), np.float32)
    out_q = np.asarray(q.predict(x), np.float32)
    denom = np.maximum(np.abs(out_ref), 1.0)
    assert np.max(np.abs(out_q - out_ref) / denom) < 0.2
    agree = np.mean(out_q.argmax(1) == out_ref.argmax(1))
    assert agree >= 0.75, agree


def test_ws_conv_stays_weight_only_under_calibration():
    """ScaledWSConv2D must NOT take the activation-quantized path (its
    weight standardization needs the float kernel): calibration must
    skip it and serving must still produce finite, close-to-f32 output."""
    import jax
    import jax.numpy as jnp
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.serving.inference_model import InferenceModel

    init_orca_context("local")
    # kernel 3*3*24*64 = 13,824 elements: ABOVE _Q_MIN_SIZE, so it
    # really is stored int8 and the WS conv must dequantize the dict
    # (a sub-threshold kernel would stay float and test nothing)
    model = nn.Sequential([
        nn.ScaledWSConv2D(64, 3, activation="relu"),
        nn.GlobalAveragePooling2D(),
        nn.Dense(8)])
    rng = np.random.default_rng(8)
    calib = rng.normal(size=(8, 12, 12, 24)).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(calib))
    q = InferenceModel().load(model, variables, dtype="int8",
                              calibrate=calib)
    # only the Dense observed — the WS conv opted out
    assert len(q._quant_ctx.amax) == 1
    ref = InferenceModel().load(model, variables)
    out_q = np.asarray(q.predict(calib), np.float32)
    out_ref = np.asarray(ref.predict(calib), np.float32)
    assert np.all(np.isfinite(out_q))
    denom = np.maximum(np.abs(out_ref), 1.0)
    assert np.max(np.abs(out_q - out_ref) / denom) < 0.2


# -- pipelined hot path (assembly → inference workers → reply writers) --------

class _PipeModel:
    """Stub with declared concurrency for pipelined-server tests: doubles
    its input, counts rows actually inferred, optional per-batch delay."""

    concurrent_num = 4

    def __init__(self, delay: float = 0.0):
        self.delay = delay
        self.calls = []
        self._lock = threading.Lock()

    def predict(self, x):
        if self.delay:
            time.sleep(self.delay)
        with self._lock:
            self.calls.append(np.asarray(x).shape[0])
        return np.asarray(x) * 2.0

    @property
    def rows_seen(self) -> int:
        with self._lock:
            return sum(self.calls)


def test_pipelined_mixed_shape_concurrent_clients():
    """inference_workers=2: concurrent clients with two feature shapes all
    get their own (correct) answer — shape groups may infer concurrently
    on different workers, replies still key by uuid."""
    with ClusterServing(_PipeModel(), batch_size=8, batch_timeout_ms=10,
                        inference_workers=2) as srv:
        assert srv.inference_workers == 2
        results, errors = {}, []

        def client(i):
            try:
                iq = InputQueue(srv.host, srv.port)
                oq = OutputQueue(input_queue=iq)
                shape = (4,) if i % 2 else (7,)
                x = np.full(shape, float(i), np.float32)
                uid = iq.enqueue(f"c{i}", t=x)
                results[i] = (shape, oq.query(uid, timeout=30.0))
                iq.close()
            except Exception as e:  # noqa: BLE001
                errors.append((i, e))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        assert len(results) == 16
        for i, (shape, out) in results.items():
            assert out.shape == shape
            np.testing.assert_allclose(out, np.full(shape, 2.0 * i),
                                       rtol=1e-6)
        s = srv.stats()
    assert s["requests"] == 16
    assert s["requests"] == s["replies"] + s["errors"] + s["pending"]


def test_stats_invariant_under_two_workers():
    """requests == replies + errors + pending must survive the pipelined
    restructure with concurrent inference workers."""
    with ClusterServing(_PipeModel(), batch_size=4, batch_timeout_ms=5,
                        inference_workers=2) as srv:
        iq = InputQueue(srv.host, srv.port)
        oq = OutputQueue(input_queue=iq)
        uids = [iq.enqueue(f"i{k}", t=np.full((6,), float(k), np.float32))
                for k in range(20)]
        for uid in uids:
            assert oq.query(uid, timeout=30.0) is not None
        s = srv.stats()
        iq.close()
    assert s["requests"] == 20 and s["pending"] == 0
    assert s["requests"] == s["replies"] + s["errors"] + s["pending"]
    assert s["inference_workers"] == 2


def test_slow_reading_client_does_not_stall_inference():
    """A client that stops reading its replies (tiny receive buffer, big
    tensors) blocks only its own connection's reply writer: other
    clients' requests keep flowing through assembly → inference → reply,
    and the slow client's own rows still get INFERRED (replies parked in
    its writer queue), because sendall no longer runs on the batcher."""
    import socket
    model = _PipeModel()
    rows = 16
    big = np.ones((262144,), np.float32)  # 1 MiB per request/reply
    with ClusterServing(model, batch_size=2, batch_timeout_ms=2,
                        inference_workers=2) as srv:
        slow = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        # shrink the receive window BEFORE connect so the server-side
        # sendall hits backpressure after a few replies
        slow.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 16384)
        slow.connect((srv.host, srv.port))
        try:
            for i in range(rows):
                protocol.send_frame(slow,
                                    protocol.encode({"uuid": f"slow-{i}"},
                                                    big))
            # ... and never read a single reply.
            # meanwhile a well-behaved client must round-trip promptly
            iq = InputQueue(srv.host, srv.port)
            oq = OutputQueue(input_queue=iq)
            t0 = time.monotonic()
            for k in range(8):
                uid = iq.enqueue(f"fast-{k}",
                                 t=np.full((8,), float(k), np.float32))
                out = oq.query(uid, timeout=30.0)
                np.testing.assert_allclose(out, np.full((8,), 2.0 * k),
                                           rtol=1e-6)
            fast_elapsed = time.monotonic() - t0
            assert fast_elapsed < 20.0
            # the slow client's rows were all inferred too — its replies
            # are queued/blocked in ITS writer, not holding the model
            deadline = time.monotonic() + 20.0
            while (model.rows_seen < rows + 8
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            assert model.rows_seen == rows + 8, model.rows_seen
            # counters are final pre-send: replies counts the stuck ones
            s = srv.stats()
            assert s["replies"] == rows + 8
            assert s["requests"] == s["replies"] + s["errors"] + s["pending"]
            iq.close()
        finally:
            slow.close()


def test_stop_drains_assembled_batches_in_internal_queue():
    """stop() with work at EVERY pipeline depth: the in-flight batch
    finishes, batches waiting in the internal assembled-batch queue and
    requests still in the native queue all get the explicit
    "server shutting down" reply — no hung queries, invariant intact."""
    from analytics_zoo_tpu.serving.client import RetryPolicy
    model = _PipeModel(delay=0.3)
    srv = ClusterServing(model, batch_size=1, batch_timeout_ms=1,
                         inference_workers=1).start()
    iq = InputQueue(srv.host, srv.port, retry=RetryPolicy(max_attempts=1))
    oq = OutputQueue(input_queue=iq)
    x = np.arange(4, dtype=np.float32)
    uids = [iq.enqueue(f"d{i}", t=x) for i in range(6)]
    time.sleep(0.15)  # first batch is inside the model; rest are staged
    outcomes = {}

    def drain_query(uid):
        try:
            outcomes[uid] = ("ok", oq.query(uid, timeout=15.0))
        except RuntimeError as e:
            outcomes[uid] = ("error", str(e))

    threads = [threading.Thread(target=drain_query, args=(u,))
               for u in uids]
    for t in threads:
        t.start()
    srv.stop()
    for t in threads:
        t.join(timeout=20)
    assert not any(t.is_alive() for t in threads), "hung query() calls"
    assert len(outcomes) == 6
    served = [u for u, (kind, _) in outcomes.items() if kind == "ok"]
    drained = [u for u, (kind, msg) in outcomes.items()
               if kind == "error" and "server shutting down" in msg]
    assert len(served) + len(drained) == 6, outcomes
    # inference_workers=1 and one batch takes 0.3s: most of the queue
    # (native + internal assembled) must have been drained, not served
    assert len(drained) >= 2
    s = srv.stats()
    assert s["drained"] == len(drained)
    assert s["requests"] == s["replies"] + s["errors"] + s["pending"] == 6
    iq.close()


def test_batch_error_reply_carries_trace_id():
    """A whole-batch inference failure must include the trace id in its
    error reply so traced clients can correlate the failure."""
    import socket

    class _Boom:
        concurrent_num = 2

        def predict(self, x):
            raise ValueError("boom-batch")

    with ClusterServing(_Boom(), batch_size=2) as srv:
        raw = socket.create_connection((srv.host, srv.port), timeout=10)
        try:
            protocol.send_frame(raw, protocol.encode(
                {"uuid": "traced-1", "trace": "feedbeeffeedbeef"},
                np.ones((4,), np.float32)))
            header, _ = protocol.decode(protocol.recv_frame(raw))
            assert header["uuid"] == "traced-1"
            assert "boom-batch" in header["error"]
            assert header["trace"] == "feedbeeffeedbeef"
        finally:
            raw.close()


def test_staging_buffers_are_reused_across_batches():
    """Batch assembly stages rows into a pooled per-shape buffer instead
    of a fresh np.stack: after sequential batches of one shape, the pool
    holds at most `staging_pool` buffers and results stay correct."""
    model = _PipeModel()
    with ClusterServing(model, batch_size=4, batch_timeout_ms=2,
                        inference_workers=1, staging_pool=2) as srv:
        iq = InputQueue(srv.host, srv.port)
        oq = OutputQueue(input_queue=iq)
        for round_i in range(6):
            uid = iq.enqueue(f"r{round_i}",
                             t=np.full((5,), float(round_i), np.float32))
            out = oq.query(uid, timeout=30.0)
            np.testing.assert_allclose(out, np.full((5,), 2.0 * round_i),
                                       rtol=1e-6)
        key = ((5,), "float32")
        with srv._staging_lock:
            pool = list(srv._staging.get(key, []))
        assert 1 <= len(pool) <= 2  # reused, bounded by staging_pool
        iq.close()


def test_worker_reshed_keeps_survivor_rows_aligned():
    """Regression (review): a deadline that expires while a batch waits
    in the INTERNAL queue sheds that row at the worker — the surviving
    request must still get the prediction for ITS OWN input, not its
    shed neighbor's (the batch is re-staged after the shed)."""
    from analytics_zoo_tpu.serving.client import RetryPolicy
    model = _PipeModel(delay=0.8)
    with ClusterServing(model, batch_size=2, batch_timeout_ms=50,
                        inference_workers=1) as srv:
        iq = InputQueue(srv.host, srv.port,
                        retry=RetryPolicy(max_attempts=1))
        oq = OutputQueue(input_queue=iq)
        # batch 1 fills immediately and occupies the single worker 0.8s
        x1 = iq.enqueue("x1", t=np.full((4,), 10.0, np.float32))
        x2 = iq.enqueue("x2", t=np.full((4,), 20.0, np.float32))
        time.sleep(0.1)
        # batch 2 = [doomed, survivor] waits in the internal queue while
        # the worker is busy; doomed's 0.25s budget expires there
        doomed = iq.enqueue("doomed", deadline=0.25,
                            t=np.full((4,), 30.0, np.float32))
        survivor = iq.enqueue("survivor",
                              t=np.full((4,), 40.0, np.float32))
        with pytest.raises(RuntimeError, match="deadline exceeded"):
            oq.query(doomed, timeout=20.0)
        out = oq.query(survivor, timeout=20.0)
        # misaligned zip would deliver 2*30 (the shed row) here
        np.testing.assert_allclose(out, np.full((4,), 80.0), rtol=1e-6)
        assert oq.query(x1, timeout=20.0) is not None
        assert oq.query(x2, timeout=20.0) is not None
        # the shed row never ran inference: 2 (first batch) + 1 survivor
        assert model.rows_seen == 3
        s = srv.stats()
        assert s["shed"] == 1
        assert s["requests"] == s["replies"] + s["errors"] + s["pending"]
        iq.close()


def test_passthrough_model_replies_do_not_alias_staging_buffer():
    """Regression (review): a model returning (a view of) its input must
    not leave reply rows aliasing the pooled staging buffer — later
    batches would overwrite queued replies.  Interleaved same-shape
    requests with distinct payloads must each get their own echo."""

    class _Identity:
        concurrent_num = 2

        def predict(self, x):
            return x  # returns the staging-buffer view itself

    with ClusterServing(_Identity(), batch_size=4, batch_timeout_ms=1,
                        inference_workers=2, staging_pool=1) as srv:
        iq = InputQueue(srv.host, srv.port)
        oq = OutputQueue(input_queue=iq)
        uids = [(i, iq.enqueue(f"e{i}",
                               t=np.full((16,), float(i), np.float32)))
                for i in range(32)]
        for i, uid in uids:
            out = oq.query(uid, timeout=30.0)
            np.testing.assert_array_equal(out, np.full((16,), float(i),
                                                       np.float32))
        iq.close()


def test_failed_batch_does_not_double_release_staging_buffer():
    """Regression (review): an exception AFTER the success-path buffer
    release (e.g. a 0-d model output breaking the reply zip) must not
    put the same buffer into the pool twice."""

    class _ZeroD:
        concurrent_num = 2

        def __init__(self):
            self.fail = True

        def predict(self, x):
            if self.fail:
                return np.float32(3.0)  # zip() over 0-d raises
            return np.asarray(x) * 2.0

    model = _ZeroD()
    with ClusterServing(model, batch_size=2, inference_workers=1,
                        staging_pool=4) as srv:
        iq = InputQueue(srv.host, srv.port)
        oq = OutputQueue(input_queue=iq)
        with pytest.raises(RuntimeError):
            oq.query(iq.enqueue("bad", t=np.ones((4,), np.float32)),
                     timeout=20.0)
        model.fail = False
        out = oq.query(iq.enqueue("good", t=np.ones((4,), np.float32)),
                       timeout=20.0)
        np.testing.assert_allclose(out, np.full((4,), 2.0), rtol=1e-6)
        key = ((4,), "float32")
        with srv._staging_lock:
            pool = list(srv._staging.get(key, []))
        assert len(set(map(id, pool))) == len(pool), "duplicate buffer"
        iq.close()


def test_writer_overflow_drops_dead_client_not_workers(monkeypatch):
    """Regression (review): a client whose reply queue stays full past
    the push grace is DROPPED — the shared inference workers (and a
    later stop()) must never block forever on one dead connection."""
    import socket
    from analytics_zoo_tpu.serving.server import _ConnWriter
    monkeypatch.setattr(_ConnWriter, "MAX_ITEMS", 8)
    monkeypatch.setattr(_ConnWriter, "PUSH_GRACE_S", 0.2)
    model = _PipeModel()
    with ClusterServing(model, batch_size=4, batch_timeout_ms=1,
                        inference_workers=2) as srv:
        dead = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        dead.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
        dead.connect((srv.host, srv.port))
        big = np.ones((65536,), np.float32)  # 256 KiB replies
        try:
            for i in range(24):  # >> queue bound + socket buffers
                protocol.send_frame(dead,
                                    protocol.encode({"uuid": f"n{i}"},
                                                    big))
            # a healthy client keeps round-tripping while (and after)
            # the dead one overflows and gets dropped
            iq = InputQueue(srv.host, srv.port)
            oq = OutputQueue(input_queue=iq)
            for k in range(6):
                uid = iq.enqueue(f"h{k}",
                                 t=np.full((8,), float(k), np.float32))
                out = oq.query(uid, timeout=30.0)
                np.testing.assert_allclose(out, np.full((8,), 2.0 * k),
                                           rtol=1e-6)
                time.sleep(0.1)
            iq.close()
        finally:
            dead.close()
        srv.stop()  # must return promptly, not deadlock on the drain
    s = srv.stats()
    assert s["requests"] == s["replies"] + s["errors"] + s["pending"]


# -- zero-copy protocol --------------------------------------------------------

def test_encode_parts_matches_encode_and_decodes():
    header = {"uuid": "zc-1", "trace": "0123456789abcdef"}
    arr = np.arange(24, dtype=np.float32).reshape(4, 6)
    joined = b"".join(protocol.encode_parts(header, arr))
    assert joined == protocol.encode(header, arr)
    got_header, got = protocol.decode(bytearray(joined[4:]))
    assert got_header["uuid"] == "zc-1"
    np.testing.assert_array_equal(got, arr)
    # non-contiguous input still encodes its logical content
    nc = np.arange(32, dtype=np.float32).reshape(8, 4)[::2]
    _, got_nc = protocol.decode(
        bytearray(b"".join(protocol.encode_parts({"uuid": "z"}, nc))[4:]))
    np.testing.assert_array_equal(got_nc, nc)


def test_send_frame_parts_handles_partial_sends():
    """Scatter-gather send must survive partial sendmsg returns (small
    socket buffers + a large tensor): the peer reassembles the exact
    frame."""
    import socket
    a, b = socket.socketpair()
    try:
        a.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 8192)
        arr = np.random.default_rng(0).normal(
            size=(1024, 64)).astype(np.float32)  # 256 KiB payload
        parts = protocol.encode_parts({"uuid": "big"}, arr)
        sender = threading.Thread(
            target=protocol.send_frame_parts, args=(a, parts))
        sender.start()
        frame = protocol.recv_frame(b)
        sender.join(timeout=10)
        assert not sender.is_alive()
        header, got = protocol.decode(frame)
        assert header["uuid"] == "big"
        np.testing.assert_array_equal(got, arr)
    finally:
        a.close()
        b.close()


def test_recv_frame_rejects_oversized_length(monkeypatch):
    """SATELLITE: a corrupt/malicious 4-byte length must be rejected
    BEFORE any allocation (configurable MAX_FRAME_BYTES), not answered
    with a multi-GiB bytearray attempt."""
    import socket
    import struct
    a, b = socket.socketpair()
    try:
        a.sendall(struct.pack(">I", protocol.MAX_FRAME_BYTES + 1))
        with pytest.raises(ValueError, match="MAX_FRAME_BYTES"):
            protocol.recv_frame(b)
    finally:
        a.close()
        b.close()
    # the bound is configurable: a legitimate frame over a lowered bound
    # is rejected the same way
    monkeypatch.setattr(protocol, "MAX_FRAME_BYTES", 64)
    a, b = socket.socketpair()
    try:
        a.sendall(protocol.encode({"uuid": "x"},
                                  np.zeros((64,), np.float32)))
        with pytest.raises(ValueError, match="MAX_FRAME_BYTES"):
            protocol.recv_frame(b)
    finally:
        a.close()
        b.close()


def test_server_survives_oversized_frame_then_serves(inference_model):
    """An oversized length prefix kills that connection only; the server
    keeps serving well-formed clients."""
    import socket
    import struct
    with ClusterServing(inference_model, batch_size=2) as srv:
        raw = socket.create_connection((srv.host, srv.port), timeout=10)
        try:
            raw.sendall(struct.pack(">I", protocol.MAX_FRAME_BYTES + 7))
            raw.settimeout(10)
            assert raw.recv(1) == b""  # server closed the connection
        finally:
            raw.close()
        iq = InputQueue(srv.host, srv.port)
        oq = OutputQueue(input_queue=iq)
        uid = iq.enqueue("ok", t=np.ones(4, np.float32))
        assert oq.query(uid, timeout=20.0) is not None
        iq.close()


def test_save_load_executables_roundtrip(tmp_path):
    """Serialized AOT artifacts (reference: OpenVINO IR) round-trip: a
    fresh InferenceModel loads them, skips tracing, and predicts the
    same values; a config mismatch (different precision) ignores them."""
    import jax
    import jax.numpy as jnp
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.serving.inference_model import InferenceModel

    init_orca_context("local")
    model = nn.Sequential([nn.Dense(32, activation="relu"), nn.Dense(4)])
    rng = np.random.default_rng(9)
    x = rng.normal(size=(4, 8)).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(x))

    src = InferenceModel().load(model, variables)
    want = np.asarray(src.predict(x))
    n = src.save_executables(str(tmp_path / "aot"))
    assert n == 1  # one (shape, dtype) bucket compiled

    dst = InferenceModel().load(model, variables)
    assert dst.load_executables(str(tmp_path / "aot")) == 1
    got = np.asarray(dst.predict(x))  # served via the deserialized artifact
    np.testing.assert_allclose(got, want, rtol=1e-6)

    # precision mismatch -> artifacts ignored, fresh compile still works
    other = InferenceModel().load(model, variables, dtype=jnp.bfloat16)
    assert other.load_executables(str(tmp_path / "aot")) == 0
    assert np.asarray(other.predict(x)).shape == want.shape


def test_load_executables_compiles_once_no_per_call_retrace(tmp_path):
    """A warm-reload artifact must dispatch a cached executable, not
    re-trace per call: load_executables wraps the deserialized
    ``exp.call`` in an AOT-compiled ``jax.stages.Compiled`` ONCE at load
    time, without counting into ``compile_count`` (the hot-swap
    acceptance treats artifact loads as free)."""
    import jax
    import jax.numpy as jnp
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.serving.inference_model import InferenceModel

    init_orca_context("local")
    model = nn.Sequential([nn.Dense(32, activation="relu"), nn.Dense(4)])
    rng = np.random.default_rng(11)
    x = rng.normal(size=(4, 8)).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(x))

    src = InferenceModel().load(model, variables)
    want = np.asarray(src.predict(x))
    assert src.save_executables(str(tmp_path / "aot")) == 1

    dst = InferenceModel().load(model, variables)
    assert dst.load_executables(str(tmp_path / "aot")) == 1
    assert dst.compile_count == 0  # artifact loads are not fresh compiles
    fns = list(dst._compiled.values())
    assert len(fns) == 1
    # the load-time wrap: a Compiled stage, not the raw re-tracing
    # exp.call bound method
    assert isinstance(fns[0], jax.stages.Compiled)
    got = np.asarray(dst.predict(x))
    np.testing.assert_allclose(got, want, rtol=1e-6)
    # repeated predicts keep dispatching the SAME cached executable
    assert dst._compiled[next(iter(dst._compiled))] is fns[0]
    assert dst.compile_count == 0


def test_load_executables_rejects_stale_model_code(tmp_path):
    """A model-code edit that leaves the variable tree identical must
    NOT silently serve the stale artifact: the traced-computation hash
    (manifest "jaxpr") catches it; verify=False trusts the artifact."""
    import jax
    import jax.numpy as jnp
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.serving.inference_model import InferenceModel

    init_orca_context("local")
    rng = np.random.default_rng(10)
    x = rng.normal(size=(4, 8)).astype(np.float32)
    relu_net = nn.Sequential([nn.Dense(16, activation="relu"),
                              nn.Dense(4)])
    gelu_net = nn.Sequential([nn.Dense(16, activation="gelu"),
                              nn.Dense(4)])  # same param tree, new math
    variables = relu_net.init(jax.random.PRNGKey(0), jnp.asarray(x))

    src = InferenceModel().load(relu_net, variables)
    src.predict(x)
    assert src.save_executables(str(tmp_path / "aot")) == 1

    stale = InferenceModel().load(gelu_net, variables)
    assert stale.load_executables(str(tmp_path / "aot")) == 0
    # and the unverified fast path loads it (caller's responsibility)
    assert stale.load_executables(str(tmp_path / "aot"),
                                  verify=False) == 1
