"""High-availability serving (ISSUE 5): replicated backends behind the
ReplicaSet router — health-checked routing, circuit breakers, failover,
hedged reads, graceful drain, and THE acceptance scenario: hard-kill a
replica under sustained load with zero client-visible failures, then a
rolling restart that drops nothing.

Determinism: faults come from per-server private FaultRegistry
instances (or the scoped global registry), retry policies are seeded,
and no injected delay exceeds 0.5 s.
"""

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from analytics_zoo_tpu.core import metrics as metrics_lib
from analytics_zoo_tpu.core import trace as trace_lib
from analytics_zoo_tpu.core.faults import FaultRegistry, get_registry
from analytics_zoo_tpu.serving import (CircuitBreaker, ClusterServing,
                                       HTTPFrontend, InputQueue,
                                       OutputQueue, ReplicaSet)
from analytics_zoo_tpu.serving.client import RetryPolicy

pytestmark = pytest.mark.faults


class _Model:
    """Doubles its input; counts the rows it actually ran."""

    def __init__(self, delay: float = 0.0):
        self.delay = delay
        self.calls = []
        self._lock = threading.Lock()

    def predict(self, x):
        if self.delay:
            time.sleep(self.delay)
        with self._lock:
            self.calls.append(np.asarray(x).shape[0])
        return np.asarray(x) * 2.0

    @property
    def rows_seen(self) -> int:
        with self._lock:
            return sum(self.calls)


def _fast_retry(**kw) -> RetryPolicy:
    kw.setdefault("max_attempts", 3)
    kw.setdefault("base_delay", 0.02)
    kw.setdefault("max_delay", 0.1)
    kw.setdefault("seed", 0)
    return RetryPolicy(**kw)


def _serve(model=None, faults=None, port=0, **kw) -> ClusterServing:
    kw.setdefault("batch_size", 8)
    kw.setdefault("batch_timeout_ms", 2)
    return ClusterServing(model or _Model(), port=port, faults=faults,
                          **kw).start()


def _restart_on_port(model, port, faults=None, timeout=15.0, **kw):
    """Start a replacement server on a just-released port (the OS may
    need a beat to free it)."""
    deadline = time.monotonic() + timeout
    while True:
        try:
            return _serve(model, faults=faults, port=port, **kw)
        except OSError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.05)


# -- circuit breaker (pure unit) ----------------------------------------------

def test_breaker_opens_after_threshold_and_recloses():
    b = CircuitBreaker(threshold=3, reset_s=0.1)
    assert b.state == "closed" and b.allow()
    b.record_failure(); b.record_failure()
    assert b.state == "closed" and b.allow()  # under threshold
    b.record_failure()
    assert b.state == "open" and b.opens == 1
    assert not b.allow()                      # open: fail fast
    time.sleep(0.12)
    assert b.allow()                          # reset elapsed: half-open probe
    assert b.state == "half-open"
    b.record_success()
    assert b.state == "closed" and b.allow()
    assert b.consecutive_failures == 0


def test_breaker_failed_probe_reopens_with_backoff():
    b = CircuitBreaker(threshold=1, reset_s=0.05, backoff_factor=2.0,
                       max_reset_s=1.0)
    b.record_failure()
    assert b.state == "open"
    time.sleep(0.06)
    assert b.allow()                          # half-open probe
    b.record_failure()                        # probe failed
    assert b.state == "open" and b.opens == 2
    assert b._timeout == pytest.approx(0.1)   # grew 2x
    assert not b.allow()                      # new window not elapsed
    time.sleep(0.11)
    assert b.allow()
    b.record_success()
    assert b.state == "closed" and b._timeout == pytest.approx(0.05)


def test_breaker_half_open_probe_budget_is_rate_limited():
    b = CircuitBreaker(threshold=1, reset_s=0.1)
    b.record_failure()
    time.sleep(0.11)
    assert b.allow()          # the transition probe
    assert not b.allow()      # second caller inside the window: rejected


# -- health pings -------------------------------------------------------------

def test_ping_round_trip_carries_state_and_depth():
    with _serve() as srv:
        iq = InputQueue(srv.host, srv.port, retry=_fast_retry())
        pong = iq.conn.ping(timeout=2.0)
        assert pong and pong.get("pong") is True
        assert pong["state"] == "serving"
        assert "queue_depth" in pong
        assert srv.stats()["pings"] == 1
        # pings never touch the request invariant
        s = srv.stats()
        assert s["requests"] == s["replies"] == s["errors"] == 0
        iq.close()


def test_health_fail_fault_swallows_the_pong():
    faults = get_registry()
    with _serve() as srv:
        iq = InputQueue(srv.host, srv.port, retry=_fast_retry())
        with faults.armed("serving.health_fail", times=1):
            assert iq.conn.ping(timeout=0.4) is None  # probe lost
        assert faults.fired("serving.health_fail") == 1
        assert iq.conn.ping(timeout=2.0) is not None  # next probe lands
        iq.close()


def test_wedged_assembly_fails_the_ping_by_timeout():
    """The reason pings ride the queue: an armed assembly-stage latency
    (the wedged-but-connected backend) delays the pong past the probe
    timeout even though the socket is perfectly healthy."""
    private = FaultRegistry()
    with _serve(faults=private) as srv:
        iq = InputQueue(srv.host, srv.port, retry=_fast_retry())
        assert iq.conn.ping(timeout=2.0) is not None  # healthy baseline
        private.enable("serving.model_latency", times=1, delay=0.4)
        assert iq.conn.ping(timeout=0.15) is None     # wedged: no pong
        iq.close()


# -- drain + admission control ------------------------------------------------

def test_drain_rejects_new_work_retryably_and_finishes_in_flight():
    model = _Model(delay=0.2)
    srv = _serve(model, batch_size=1, batch_timeout_ms=1)
    iq = InputQueue(srv.host, srv.port, retry=_fast_retry(max_attempts=2))
    oq = OutputQueue(input_queue=iq)
    x = np.arange(4, dtype=np.float32)
    uid_in = iq.enqueue("in-flight", t=x)
    time.sleep(0.05)  # the request reaches the pipeline
    assert srv.drain(wait=False)
    assert srv.state == "draining"
    # a health pong reports the drain BEFORE any rejection happens
    assert iq.conn.ping(timeout=2.0)["state"] == "draining"
    uid_new = iq.enqueue("late", t=x)
    with pytest.raises(RuntimeError, match="draining"):
        oq.query(uid_new, timeout=10.0)
    # the admitted request still completes, and drain(wait) observes it
    assert srv.drain(wait=True, timeout=10.0)
    np.testing.assert_allclose(oq.query(uid_in, timeout=10.0), x * 2.0)
    s = srv.stats()
    assert s["draining_rejected"] >= 1
    assert s["requests"] == s["replies"] + s["errors"]
    srv.stop()
    iq.close()


def test_admission_queue_limit_rejects_retryably():
    private = FaultRegistry()
    model = _Model()
    with _serve(model, batch_size=1, batch_timeout_ms=1,
                admission_queue_limit=1, faults=private) as srv:
        iq = InputQueue(srv.host, srv.port,
                        retry=_fast_retry(max_attempts=1))
        oq = OutputQueue(input_queue=iq)
        x = np.arange(4, dtype=np.float32)
        # wedge assembly so the queue actually builds depth
        private.enable("serving.model_latency", times=1, delay=0.4)
        uid_a = iq.enqueue("a", t=x)      # popped, wedged in assembly
        time.sleep(0.05)
        uid_b = iq.enqueue("b", t=x)      # sits in the queue (depth 1)
        time.sleep(0.05)
        uid_c = iq.enqueue("c", t=x)      # over the soft cap
        with pytest.raises(RuntimeError, match="queue full"):
            oq.query(uid_c, timeout=10.0)
        assert oq.query(uid_a, timeout=10.0) is not None
        assert oq.query(uid_b, timeout=10.0) is not None
        assert srv.stats()["admission_rejected"] >= 1
        iq.close()


def test_admission_rejects_unattainable_deadline():
    """A request whose whole budget is below the observed queue wait is
    rejected at the door — not queued, not inferred, not shed later."""
    private = FaultRegistry()
    model = _Model()
    with _serve(model, batch_size=1, batch_timeout_ms=1,
                faults=private) as srv:
        iq = InputQueue(srv.host, srv.port,
                        retry=_fast_retry(max_attempts=1))
        oq = OutputQueue(input_queue=iq)
        x = np.arange(4, dtype=np.float32)
        private.enable("serving.model_latency", times=3, delay=0.3)
        uid_a = iq.enqueue("a", t=x)          # wedges assembly
        time.sleep(0.02)
        uid_b = iq.enqueue("b", t=x)          # waits ~0.3s -> EWMA rises
        rows_before = model.rows_seen
        # wait until B was assembled (EWMA now reflects its queue wait)
        deadline = time.monotonic() + 5
        while model.rows_seen < rows_before + 1 and \
                time.monotonic() < deadline:
            time.sleep(0.02)
        uid_c = iq.enqueue("c", t=x)          # keeps queue depth >= 1
        uid_d = iq.enqueue("doomed", deadline=0.01, t=x)
        with pytest.raises(RuntimeError, match="deadline unattainable"):
            oq.query(uid_d, timeout=10.0)
        for uid in (uid_a, uid_b, uid_c):
            assert oq.query(uid, timeout=10.0) is not None
        s = srv.stats()
        assert s["admission_rejected"] == 1
        assert s["requests"] == s["replies"] + s["errors"]
        iq.close()


# -- replica set: routing + health --------------------------------------------

def _replica_set(servers, **kw):
    kw.setdefault("retry", _fast_retry())
    kw.setdefault("health_interval", 0.08)
    kw.setdefault("health_timeout", 0.5)
    kw.setdefault("breaker_reset_s", 0.25)
    return ReplicaSet([(s.host, s.port) for s in servers], **kw)


def test_replica_set_routes_and_both_replicas_serve():
    m1, m2 = _Model(delay=0.03), _Model(delay=0.03)
    s1, s2 = _serve(m1, batch_size=1, batch_timeout_ms=1), \
        _serve(m2, batch_size=1, batch_timeout_ms=1)
    rs = _replica_set([s1, s2])
    errors = []

    def client(i):
        x = np.full((4,), float(i), np.float32)
        for _ in range(6):
            try:
                np.testing.assert_allclose(rs.predict(x, timeout=15.0),
                                           x * 2.0)
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))

    try:
        # concurrent clients: least-pending routing only spreads load
        # when requests overlap (a serial loop correctly pins the
        # emptiest — i.e. always the same — replica)
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors[:3]
        assert m1.rows_seen > 0 and m2.rows_seen > 0
        assert m1.rows_seen + m2.rows_seen == 24
        hz = rs.healthz()
        assert hz["status"] == "ok"
        assert all(v["available"] for v in hz["replicas"].values())
    finally:
        rs.close()
        s1.stop()
        s2.stop()


def test_health_checker_ejects_wedged_replica_and_readmits_it():
    """Arm assembly latency on one replica: its pongs stop arriving, the
    health checker ejects it, traffic flows to the sibling with zero
    failures, and the first pong after the wedge clears re-admits it."""
    private = FaultRegistry()
    m1, m2 = _Model(), _Model()
    s1 = _serve(m1, faults=private)
    s2 = _serve(m2)
    rs = _replica_set([s1, s2], health_timeout=0.15)
    name1 = f"{s1.host}:{s1.port}"
    try:
        x = np.arange(4, dtype=np.float32)
        assert rs.predict(x, timeout=10.0) is not None
        private.enable("serving.model_latency", times=5, delay=0.4)
        deadline = time.monotonic() + 10
        while rs.healthz()["replicas"][name1]["healthy"]:
            assert time.monotonic() < deadline, "replica never ejected"
            time.sleep(0.02)
        # ejected: every request is served by the sibling, none fail
        for _ in range(6):
            assert rs.predict(x, timeout=10.0) is not None
        snap = metrics_lib.get_registry().snapshot()
        assert snap[f"router.health_ejections{{replica={name1}}}"] >= 1
        # charges exhaust -> pongs flow again -> re-admitted
        deadline = time.monotonic() + 15
        while not rs.healthz()["replicas"][name1]["healthy"]:
            assert time.monotonic() < deadline, "replica never re-admitted"
            time.sleep(0.05)
    finally:
        rs.close()
        s1.stop()
        s2.stop()


def test_hedged_read_wins_on_a_slow_replica():
    """A deadline'd request that has waited ``hedge_ms`` is re-enqueued
    on the second replica; the fast replica's answer wins."""
    # pin the pick order: least-pending ties break on the name STRING,
    # so give the slow model the lexicographically smaller address
    ports = []
    for _ in range(2):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        s.close()
    ports.sort(key=lambda p: f"127.0.0.1:{p}")
    slow, fast = _Model(delay=0.4), _Model()
    s1 = _serve(slow, port=ports[0], batch_size=1, batch_timeout_ms=1)
    s2 = _serve(fast, port=ports[1], batch_size=1, batch_timeout_ms=1)
    rs = _replica_set([s1, s2], hedge_ms=50.0, start_health=False)
    try:
        x = np.arange(4, dtype=np.float32)
        tid = trace_lib.new_trace_id()
        t0 = time.monotonic()
        out = rs.predict(x, deadline=5.0, trace_id=tid, timeout=10.0)
        elapsed = time.monotonic() - t0
        np.testing.assert_allclose(out, x * 2.0)
        assert fast.rows_seen >= 1          # the hedge replica answered
        assert elapsed < 0.35, elapsed      # won before the slow reply
        # the slow replica WAS picked first: its model is still inside
        # the 0.4s sleep at win time, so poll for its (duplicate) call
        deadline = time.monotonic() + 5
        while slow.rows_seen < 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert slow.rows_seen >= 1
        snap = metrics_lib.get_registry().snapshot()
        assert snap["router.hedges"] >= 1
        assert snap["router.hedge_wins"] >= 1
        # the trace names the replica that actually served it
        router_recs = [r for r in trace_lib.find(tid)
                       if r.where == "router"]
        assert router_recs, "router trace record missing"
        assert router_recs[-1].stages["router.replica"] == \
            f"{s2.host}:{s2.port}"
    finally:
        rs.close()
        s1.stop()
        s2.stop()


# -- client replay cap (satellite) --------------------------------------------

def test_replay_cap_fails_uid_visibly_instead_of_looping_forever():
    """A backend that drops the connection on every delivery would make
    ``_replay_inflight`` resend the same frame on every reconnect,
    forever.  The cap (RetryPolicy.max_attempts) fails the uid with a
    visible error reply and surfaces ``client.replayed``."""
    faults = get_registry()
    model = _Model()
    with _serve(model) as srv:
        retry = _fast_retry(max_attempts=3)
        iq = InputQueue(srv.host, srv.port, retry=retry)
        oq = OutputQueue(input_queue=iq)
        with faults.armed("serving.conn_drop"):  # drop EVERY frame
            uid = iq.enqueue("t", t=np.ones(4, np.float32))
            with pytest.raises(RuntimeError,
                               match="replay budget exhausted"):
                oq.query(uid, timeout=30.0)
        assert iq.conn.stats["replayed"] == retry.max_attempts
        snap = metrics_lib.get_registry().snapshot()
        assert snap["client.replayed"] == retry.max_attempts
        # the connection itself is still usable afterwards
        uid2 = iq.enqueue("after", t=np.ones(4, np.float32))
        assert oq.query(uid2, timeout=20.0) is not None
        iq.close()


# -- shutdown races (satellite) -----------------------------------------------

def test_stop_during_client_reconnect_terminates_bounded():
    """``ClusterServing.stop()`` racing a client mid-``reconnect()``:
    every query thread terminates within a bounded time — served, an
    explicit error, or a timeout — and the server's counter invariant
    holds."""
    model = _Model(delay=0.2)
    faults = get_registry()
    srv = _serve(model, batch_size=1, batch_timeout_ms=1)
    iq = InputQueue(srv.host, srv.port, retry=_fast_retry())
    oq = OutputQueue(input_queue=iq)
    x = np.arange(4, dtype=np.float32)
    uids = [iq.enqueue(f"r{i}", t=x) for i in range(3)]
    # the NEXT frame the server sees kills this connection: the client
    # enters its reconnect path while we stop() the server underneath
    faults.enable("serving.conn_drop", times=1)
    iq.enqueue("dropper", t=x)
    outcomes = {}

    def q(uid):
        try:
            outcomes[uid] = ("ok", oq.query(uid, timeout=10.0))
        except (RuntimeError, OSError) as e:
            outcomes[uid] = ("error", str(e))

    threads = [threading.Thread(target=q, args=(u,)) for u in uids]
    for t in threads:
        t.start()
    time.sleep(0.05)
    srv.stop()
    for t in threads:
        t.join(timeout=20)
    faults.disable("serving.conn_drop")  # the charge may be unspent
    assert not any(t.is_alive() for t in threads), "hung query() calls"
    assert len(outcomes) == 3, outcomes
    s = srv.stats()
    assert s["pending"] == 0
    assert s["requests"] == s["replies"] + s["errors"]
    iq.close()


def test_frontend_close_with_hedged_request_in_flight_is_bounded():
    """``HTTPFrontend.close()`` while a hedged request is outstanding on
    BOTH replicas: the in-flight predict raises promptly instead of
    waiting out its timeout, and close() itself returns."""
    slow1, slow2 = _Model(delay=1.0), _Model(delay=1.0)
    s1 = _serve(slow1, batch_size=1, batch_timeout_ms=1)
    s2 = _serve(slow2, batch_size=1, batch_timeout_ms=1)
    rs = _replica_set([s1, s2], hedge_ms=30.0, start_health=False)
    fe = HTTPFrontend(router=rs).start()
    outcome = {}

    def call():
        try:
            outcome["result"] = fe.predict(
                np.arange(4, dtype=np.float32), deadline=8.0)
        except OSError as e:
            outcome["error"] = str(e)

    t = threading.Thread(target=call)
    t.start()
    time.sleep(0.3)  # request sent; hedge_ms elapsed -> hedge launched
    t0 = time.monotonic()
    fe.close()
    close_s = time.monotonic() - t0
    t.join(timeout=5)
    assert not t.is_alive(), "predict hung past close()"
    assert close_s < 3.0, close_s
    assert "error" in outcome and "closed" in outcome["error"], outcome
    s1.stop()
    s2.stop()


# -- THE acceptance test ------------------------------------------------------

def test_ha_acceptance_replica_kill_and_rolling_restart_zero_failures():
    """ISSUE 5 acceptance: 2 replicas behind the router under sustained
    load; hard-kill one (``serving.replica_down``) → ZERO client-visible
    failures, the dead replica's circuit opens and re-closes when it
    returns; then a scripted rolling restart (drain → stop → start, one
    replica at a time) completes with 0 dropped requests, ``/healthz``
    reflecting the state transitions throughout."""
    f1 = FaultRegistry()
    servers = [_serve(_Model(), faults=f1), _serve(_Model())]
    names = [f"{s.host}:{s.port}" for s in servers]
    ports = [s.port for s in servers]
    rs = ReplicaSet([(s.host, s.port) for s in servers],
                    retry=_fast_retry(max_attempts=4),
                    health_interval=0.08, health_timeout=0.5,
                    breaker_threshold=3, breaker_reset_s=0.2)
    fe = HTTPFrontend(router=rs).start()
    url = f"http://{fe.host}:{fe.port}/healthz"

    stop_load = threading.Event()
    failures, served = [], []
    hz_samples = []

    def load(i):
        x = np.full((4,), float(i), np.float32)
        while not stop_load.is_set():
            try:
                out = fe.predict(x, deadline=15.0)
            except Exception as e:  # noqa: BLE001 — the failure record
                failures.append(f"{type(e).__name__}: {e}")
                continue
            if out is None:
                failures.append("timeout")
            else:
                served.append(1)

    def poll_healthz():
        while not stop_load.is_set():
            try:
                with urllib.request.urlopen(url, timeout=5) as r:
                    hz_samples.append(json.load(r))
            except urllib.error.HTTPError as e:
                hz_samples.append(json.load(e))
            except OSError:
                pass
            time.sleep(0.04)

    threads = [threading.Thread(target=load, args=(i,)) for i in range(4)]
    poller = threading.Thread(target=poll_healthz)
    for t in threads + [poller]:
        t.start()
    try:
        time.sleep(0.4)                      # steady state, both serving
        n_steady = len(served)
        assert n_steady > 0 and not failures

        # ---- phase 1: hard-kill replica 0 under load --------------------
        f1.enable("serving.replica_down", times=1)
        deadline = time.monotonic() + 10
        while not servers[0]._stop.is_set():
            assert time.monotonic() < deadline, "kill fault never fired"
            time.sleep(0.01)
        time.sleep(0.6)                      # load keeps flowing degraded
        hz = rs.healthz()
        assert not hz["replicas"][names[0]]["available"]
        # the circuit opened (breaker) — the dead replica costs nothing
        snap = metrics_lib.get_registry().snapshot()
        assert snap.get(f"router.breaker_opens{{replica={names[0]}}}",
                        0) >= 1

        # ---- replica returns: circuit re-closes, health re-admits -------
        servers[0] = _restart_on_port(_Model(), ports[0])
        deadline = time.monotonic() + 20
        while True:
            rep = rs.healthz()["replicas"][names[0]]
            if rep["available"] and rep["breaker"] == "closed":
                break
            assert time.monotonic() < deadline, \
                f"replica never re-admitted: {rep}"
            time.sleep(0.05)

        # ---- phase 2: rolling restart under load ------------------------
        for i, _ in enumerate(servers):
            srv = servers[i]
            assert srv.drain(timeout=15.0), "drain never settled"
            srv.stop()
            servers[i] = _restart_on_port(_Model(), ports[i])
            deadline = time.monotonic() + 20
            while True:
                rep = rs.healthz()["replicas"][names[i]]
                if rep["available"] and rep["breaker"] == "closed":
                    break
                assert time.monotonic() < deadline, \
                    f"replica {names[i]} never returned: {rep}"
                time.sleep(0.05)
        time.sleep(0.3)                      # post-restart steady state
    finally:
        stop_load.set()
        for t in threads + [poller]:
            t.join(timeout=20)
        fe.stop()
        for s in servers:
            s.stop()
    assert not any(t.is_alive() for t in threads + [poller])

    # ZERO client-visible failures across kill + rolling restart
    assert failures == [], failures[:5]
    assert len(served) > n_steady            # load really ran throughout
    # /healthz reflected the transitions: degraded (or down) while a
    # replica was out, ok at the end, and the drain state was observable
    statuses = [h["status"] for h in hz_samples]
    assert "degraded" in statuses or "down" in statuses
    assert statuses[-1] == "ok", statuses[-10:]
    seen_states = {rep["state"] for h in hz_samples
                   for rep in h["replicas"].values()}
    assert "draining" in seen_states or "stopped" in seen_states, \
        seen_states
    # both final replicas took traffic after the restarts
    assert all(s.stats()["replies"] > 0 for s in servers)
