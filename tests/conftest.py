"""Test fixtures: an 8-device CPU "cluster in a box".

Reference test strategy (SURVEY.md §4): the universal trick was ``local[N]``
Spark + Ray local mode so real all-reduce code paths run as processes on one
machine.  The TPU-native analog is an 8-device virtual CPU mesh — real XLA
collectives (psum/all_gather/ppermute) execute, no hardware needed.

Env vars must be set before jax initializes its backends, hence at import.
"""

import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

# The environment's sitecustomize may import jax (and register a TPU platform)
# before this conftest runs, making the env vars above too late; the config
# update below works as long as no backend has been *used* yet.
jax.config.update("jax_platforms", "cpu")
import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_context():
    """Each test starts with no global context."""
    from analytics_zoo_tpu.core import stop_orca_context
    stop_orca_context()
    yield
    stop_orca_context()


@pytest.fixture(autouse=True)
def _telemetry_reset():
    """Each test reads a zeroed metrics registry and trace ring: the
    registry is process-global and tests assert absolute counts.
    ``reset()`` zeroes values in place, so handles cached by long-lived
    objects (a module-scoped server fixture) stay valid."""
    from analytics_zoo_tpu.core import metrics, trace
    metrics.get_registry().reset()
    metrics.get_registry().enabled = True
    trace.reset()
    trace.enabled = True
    yield


@pytest.fixture(autouse=True)
def _fault_registry_disarmed():
    """Suite hygiene: a test that arms a fault-injection point must disarm
    it (use ``registry.armed(...)`` — it always does).  A leaked armed
    fault fails the test that leaked it, not the innocent test 200 ids
    later that trips over it."""
    yield
    from analytics_zoo_tpu.core import faults
    reg = faults.get_registry()
    storms = reg.running_schedules()
    if storms:
        # ISSUE 14: a leaked chaos storm keeps ARMING points from its
        # background thread, so stop the storms before the armed-point
        # sweep below (each stop() disarms its own points).
        names = reg.schedule_state()
        for storm in storms:
            try:
                storm.stop()
            except Exception:  # noqa: BLE001 — hygiene must not mask
                pass
        reg.reset()
        pytest.fail(f"test leaked running chaos schedule(s): {names} "
                    "(use the ChaosSchedule context manager or call "
                    "stop() in teardown)")
    leaked = reg.armed_points()
    if leaked:
        reg.reset()  # disarm so subsequent tests run clean
        pytest.fail(f"test leaked armed fault injection points: {leaked} "
                    "(arm with registry.armed(...) or disable() in "
                    "teardown)")


@pytest.fixture(autouse=True)
def _no_leaked_controllers():
    """Suite hygiene (ISSUE 12): a test that starts a ServingController
    must stop it (``controller.close()`` / the context manager).  A
    leaked supervision thread keeps ticking against the shared metrics
    registry and can scale replicas during LATER tests — fail the test
    that leaked it, after stopping the thread so the rest of the suite
    runs clean."""
    yield
    from analytics_zoo_tpu.serving import controller as controller_lib
    leaked = controller_lib.live_controllers()
    if leaked:
        for c in leaked:
            c.stop()
        pytest.fail("test leaked running ServingController thread(s): "
                    f"{leaked} (call controller.close() or use it as a "
                    "context manager)")


@pytest.fixture(autouse=True, scope="module")
def _bound_accumulated_state():
    """Full-suite hygiene: 360+ tests in one process accumulate jit
    executables and native-side state; unbounded growth intermittently
    aborts the interpreter deep into the run (observed as 'Fatal Python
    error: Aborted' inside a trace).  Clearing jax's caches per MODULE
    bounds it at the cost of some recompiles."""
    yield
    import gc
    jax.clear_caches()
    gc.collect()


@pytest.fixture
def rng():
    return np.random.default_rng(0)
