"""Friesian FeatureTable (VERDICT r1 missing #6): categorical encoding,
crosses, negative sampling, splits — feeding NeuralCF end-to-end.
"""

import numpy as np
import pandas as pd
import pytest

from analytics_zoo_tpu.core import init_orca_context
from analytics_zoo_tpu.friesian import FeatureTable, StringIndex


def _ratings_df(n=64, n_users=6, n_items=10, seed=0):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "user": [f"u{int(i)}" for i in rng.integers(0, n_users, n)],
        "item": [f"i{int(i)}" for i in rng.integers(0, n_items, n)],
        "category": rng.choice(["sports", "news", None], n),
        "age": rng.choice([22.0, 35.0, np.nan], n),
    })


def test_fillna_and_clip():
    tbl = FeatureTable.from_pandas(_ratings_df())
    filled = tbl.fillna(0.0, columns=["age"])
    assert not filled.to_pandas()["age"].isna().any()
    clipped = filled.clip(["age"], min=25.0, max=30.0)
    ages = clipped.to_pandas()["age"]
    assert ages.min() >= 25.0 and ages.max() <= 30.0
    # original untouched (ops return new tables)
    assert tbl.to_pandas()["age"].isna().any()


def test_gen_string_idx_and_encode():
    tbl = FeatureTable.from_pandas(_ratings_df())
    (user_idx, item_idx) = tbl.gen_string_idx(["user", "item"])
    assert isinstance(user_idx, StringIndex)
    assert user_idx.size == len(user_idx.index) + 1
    enc, idxs = tbl.encode_string(["user", "item"],
                                  indices=[user_idx, item_idx])
    df = enc.to_pandas()
    assert df["user"].dtype == np.int64
    assert df["user"].min() >= 1          # 0 reserved for unseen
    assert df["user"].max() <= user_idx.size - 1
    # consistent encoding across splits: same value → same id
    df_raw = tbl.to_pandas()
    m = {v: k for v, k in user_idx.index.items()}
    for raw, code in zip(df_raw["user"], df["user"]):
        assert user_idx.index[raw] == code
    # unseen values map to 0
    other = FeatureTable.from_pandas(pd.DataFrame({"user": ["uNEW"],
                                                   "item": ["i0"]}))
    enc2, _ = other.encode_string(["user", "item"], indices=idxs)
    assert enc2.to_pandas()["user"].iloc[0] == 0


def test_cross_columns_stable_and_bucketed():
    tbl = FeatureTable.from_pandas(_ratings_df())
    crossed = tbl.cross_columns([["user", "item"]], [16])
    df = crossed.to_pandas()
    assert "user_item" in df.columns
    assert df["user_item"].between(0, 15).all()
    # deterministic: same input → same hash (run twice)
    df2 = tbl.cross_columns([["user", "item"]], [16]).to_pandas()
    np.testing.assert_array_equal(df["user_item"], df2["user_item"])


def test_negative_sample():
    tbl = FeatureTable.from_pandas(_ratings_df(n=32))
    enc, idxs = tbl.encode_string(["user", "item"])
    item_size = idxs[1].size
    sampled = enc.negative_sample(item_size=item_size, item_col="item",
                                  neg_num=2)
    df = sampled.to_pandas()
    assert len(df) == 32 * 3              # 1 positive + 2 negatives per row
    assert set(df["label"].unique()) == {0, 1}
    assert (df["label"] == 1).sum() == 32
    assert df[df["label"] == 0]["item"].between(1, item_size - 1).all()


def test_random_split():
    tbl = FeatureTable.from_pandas(_ratings_df(n=200))
    train, test = tbl.random_split([0.8, 0.2], seed=1)
    assert len(train) + len(test) == 200
    assert 120 <= len(train) <= 190       # loose stochastic bounds


def test_feature_table_trains_neuralcf():
    """The NCF BASELINE config's tabular half: FeatureTable → NeuralCF via
    the unified estimator."""
    from analytics_zoo_tpu.models import NeuralCF
    from analytics_zoo_tpu.orca.learn import Estimator
    init_orca_context("local")
    tbl = FeatureTable.from_pandas(_ratings_df(n=64))
    enc, idxs = tbl.encode_string(["user", "item"])
    user_size, item_size = idxs[0].size, idxs[1].size
    data = enc.negative_sample(item_size=item_size, item_col="item")
    feed = data.to_feed(feature_cols=["user", "item"], label_col="label",
                        batch_size=32)
    model = NeuralCF(user_count=user_size, item_count=item_size,
                     class_num=2, hidden_layers=(16, 8))
    est = Estimator.from_keras(model,
                               loss="sparse_categorical_crossentropy",
                               learning_rate=1e-2)
    hist = est.fit(feed, epochs=2, batch_size=32, verbose=False)
    assert np.isfinite(hist["loss"][-1])
    x = data.to_numpy_dict(["user", "item"])["x"]
    preds = est.predict(x[:16].astype(np.int32), batch_size=16)
    assert preds.shape == (16, 2)


def test_cross_columns_feed_wide_and_deep():
    """W&D BASELINE config's wide half: Friesian crosses -> WideAndDeep
    (reference: friesian cross_columns + WideAndDeep wide_cross_dims)."""
    from analytics_zoo_tpu.models import WideAndDeep
    from analytics_zoo_tpu.orca.learn import Estimator
    init_orca_context("local")
    rng = np.random.default_rng(0)
    n, cross_dim = 96, 16
    df = pd.DataFrame({
        "user": [f"u{i}" for i in rng.integers(0, 12, n)],
        "item": [f"i{i}" for i in rng.integers(0, 9, n)],
        "age": rng.normal(35, 10, n).astype(np.float64),
        "label": rng.integers(0, 2, n),
    })
    tbl = FeatureTable.from_pandas(df)
    tbl, idxs = tbl.encode_string(["user", "item"])
    tbl = tbl.cross_columns([["user", "item"]], [cross_dim])
    out = tbl.to_pandas()
    # layout: [wide cross multi-hot | embed ids (user,item) | continuous]
    wide = np.zeros((n, cross_dim), np.float32)
    wide[np.arange(n), out["user_item"].to_numpy()] = 1.0
    x = np.concatenate([
        wide,
        out[["user", "item"]].to_numpy(np.float32),
        out[["age"]].to_numpy(np.float32),
    ], axis=1)
    y = out["label"].to_numpy(np.int32)
    model = WideAndDeep(class_num=2, wide_cross_dims=[cross_dim],
                        embed_in_dims=[idxs[0].size, idxs[1].size],
                        embed_out_dims=[8, 8], continuous_cols=1)
    est = Estimator.from_keras(model,
                               loss="sparse_categorical_crossentropy",
                               learning_rate=1e-2, metrics=["accuracy"])
    hist = est.fit((x, y), epochs=2, batch_size=32, verbose=False)
    assert np.isfinite(hist["loss"][-1])
    assert est.predict(x, batch_size=32).shape == (n, 2)


# -- determinism & shard invariance (sharded-embedding recsys path) ----------

def test_cross_hash_is_fixed_fnv_not_process_salted():
    """Hashed crosses must be reproducible across processes and releases:
    hard-coded FNV-1a regression values, NOT python's salted hash()."""
    from analytics_zoo_tpu.friesian.table import _stable_hash
    assert _stable_hash("u1_i1") == 4595758986926148594
    assert _stable_hash("u2_i3") == 1669683716010366719
    assert _stable_hash("a_b_c") == 2048235475453274411
    df = pd.DataFrame({"user": ["u1", "u2"], "item": ["i1", "i3"]})
    out = FeatureTable.from_pandas(df, num_shards=1) \
        .cross_columns([("user", "item")], [16]).to_pandas()
    assert list(out["user_item"]) == [2, 15]


def test_negative_sample_seed_reproducible_and_seed_sensitive():
    df = _ratings_df(n=48)
    tbl = FeatureTable.from_pandas(df)
    enc, idxs = tbl.encode_string(["user", "item"])
    size = idxs[1].size
    a = enc.negative_sample(size, item_col="item", neg_num=2,
                            seed=11).to_pandas()
    b = enc.negative_sample(size, item_col="item", neg_num=2,
                            seed=11).to_pandas()
    c = enc.negative_sample(size, item_col="item", neg_num=2,
                            seed=12).to_pandas()
    pd.testing.assert_frame_equal(a, b)
    assert not a["item"].equals(c["item"])


def test_negative_sample_invariant_to_shard_count():
    """The same rows with the same seed must draw the same negatives on
    1 shard and on 4 (counter-based sampling keyed on GLOBAL row
    position): the 1-shard debug run reproduces the sharded job."""
    df = _ratings_df(n=60)
    outs = []
    for shards in (1, 4):
        tbl = FeatureTable.from_pandas(df, num_shards=shards)
        enc, idxs = tbl.encode_string(
            ["user", "item"],
            indices=FeatureTable.from_pandas(df, num_shards=1)
            .gen_string_idx(["user", "item"]))
        out = enc.negative_sample(idxs[1].size, item_col="item",
                                  neg_num=2, seed=5).to_pandas()
        outs.append(out.sort_values(list(out.columns))
                    .reset_index(drop=True))
    pd.testing.assert_frame_equal(outs[0], outs[1])


def test_negative_sample_rejects_tiny_item_space():
    tbl = FeatureTable.from_pandas(pd.DataFrame({"item": [1], "x": [0]}))
    with pytest.raises(ValueError, match="item_size"):
        tbl.negative_sample(item_size=1, item_col="item")


def test_feature_ops_invariant_to_shard_count():
    """encode/fillna/clip/cross produce identical tables on 1 vs 4
    shards (vocab building is a global reduce; per-row ops are local)."""
    df = _ratings_df(n=50)
    outs = []
    for shards in (1, 4):
        tbl = FeatureTable.from_pandas(df, num_shards=shards)
        t2, _ = tbl.fillna(0.0, ["age"]).clip(["age"], min=0, max=100) \
            .encode_string(["user", "item"])
        outs.append(t2.cross_columns([("user", "item")], [50]).to_pandas())
    pd.testing.assert_frame_equal(outs[0], outs[1])


def test_feature_pipeline_matches_feature_table():
    """FeaturePipeline replays the fitted offline transforms per request
    with IDENTICAL semantics (same hash, unseen -> 0, same fill/clip)."""
    from analytics_zoo_tpu.friesian import FeaturePipeline
    df = _ratings_df(n=40)
    tbl = FeatureTable.from_pandas(df)
    idx_u, idx_i = tbl.gen_string_idx(["user", "item"])
    off, _ = tbl.fillna(0.0, ["age"]).clip(["age"], min=0, max=30) \
        .encode_string(["user", "item"], [idx_u, idx_i])
    off = off.cross_columns([("user", "item")], [50]).to_pandas()
    pipe = (FeaturePipeline().fillna(0.0, ["age"])
            .clip(["age"], min=0, max=30)
            .encode_string(idx_u).encode_string(idx_i)
            .cross_columns([("user", "item")], [50]))
    ev = pipe.transform([{"user": u, "item": i, "age": a}
                         for u, i, a in zip(df.user, df.item, df.age)])
    for col in ("user", "item", "age", "user_item"):
        np.testing.assert_array_equal(np.asarray(ev[col], np.float64),
                                      off[col].to_numpy(np.float64))


def test_feature_pipeline_pickles_and_maps_unseen_to_zero():
    import pickle
    from analytics_zoo_tpu.friesian import FeaturePipeline
    tbl = FeatureTable.from_pandas(_ratings_df(n=24))
    idx_u, idx_i = tbl.gen_string_idx(["user", "item"])
    pipe = (FeaturePipeline().fillna(0.0, ["age"])
            .encode_string(idx_u).encode_string(idx_i))
    pipe = pickle.loads(pickle.dumps(pipe))
    out = pipe.transform({"user": "NEVER_SEEN", "item": "i0",
                          "age": None})
    assert out["user"][0] == 0
    assert out["item"][0] == idx_i.index["i0"]
    assert out["age"][0] == 0.0


def test_feature_pipeline_matrix_layout_and_validation():
    """transform_matrix: the serving wire layout [B, C] with repeated
    column names (one user + k item positions), crosses appended."""
    from analytics_zoo_tpu.friesian import FeaturePipeline
    idx = StringIndex("item", {"a": 1, "b": 2})
    pipe = FeaturePipeline().encode_string(idx)
    x = np.array([["a", "b", "zz"]], dtype=object)
    out = pipe.transform_matrix(x, ["item", "item", "item"],
                                dtype=np.int64)
    np.testing.assert_array_equal(out, [[1, 2, 0]])
    with pytest.raises(ValueError, match="column"):
        pipe.transform_matrix(x, ["item"])
    with pytest.raises(ValueError, match="bucket size"):
        FeaturePipeline().cross_columns([("a", "b")], [4, 5])
