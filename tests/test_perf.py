"""Performance regression guards for the pipelined hot paths (marked
``slow`` — excluded from tier-1, run by the full suite / CI perf job).

These assert the DIRECTION of the two tentpole wins on a tiny model so a
regression fails a test instead of only bending a bench-trajectory
curve:

- serving: closed-loop throughput with ``inference_workers=2`` must not
  fall below the ``inference_workers=1`` baseline (and with a
  compute-bound stub it should clearly exceed it);
- scheduling (ISSUE 6): ``ContinuousScheduler`` must meet or beat the
  ``WindowScheduler`` on closed-loop throughput at saturation, and cut
  p50 at light load (the window tail is pure latency when the batch
  can't fill);
- training: ``fit(prefetch=2)`` must cut ``train.data_wait_ms`` versus
  ``prefetch=0`` on a throttled feed;
- streaming input (ISSUE 7): the shm-pool PROCESS decode backend must
  reach >= 2x the threaded backend's feed throughput on a GIL-bound
  synthetic decoder (threads serialize at ~1 core; processes scale
  across the host — skipped on hosts without enough cores to show it).
"""

import os
import threading
import time

import numpy as np
import pytest

import analytics_zoo_tpu.nn as nn
from analytics_zoo_tpu.core import faults, init_orca_context, metrics
from analytics_zoo_tpu.orca.learn import Estimator
from analytics_zoo_tpu.serving import ClusterServing, InputQueue, OutputQueue

pytestmark = pytest.mark.slow


class _BusyModel:
    """Fixed per-batch compute stand-in: with batch_size=1 the server is
    model-bound, so doubling inference workers should ~double QPS."""

    concurrent_num = 4

    def __init__(self, per_batch_s: float = 0.02):
        self.per_batch_s = per_batch_s

    def predict(self, x):
        time.sleep(self.per_batch_s)
        return np.asarray(x) * 2.0


def _closed_loop_qps(workers: int, duration_s: float = 2.0,
                     clients: int = 4) -> float:
    with ClusterServing(_BusyModel(), batch_size=1, batch_timeout_ms=1,
                        inference_workers=workers) as srv:
        done = []
        deadline = time.monotonic() + duration_s

        def client(i):
            iq = InputQueue(srv.host, srv.port)
            oq = OutputQueue(input_queue=iq)
            n = 0
            while time.monotonic() < deadline:
                uid = iq.enqueue(f"c{i}", t=np.ones((4,), np.float32))
                if oq.query(uid, timeout=30.0) is not None:
                    n += 1
            iq.close()
            done.append(n)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(clients)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        wall = time.monotonic() - t0
    return sum(done) / wall


def test_pipelined_serving_throughput_beats_single_worker():
    qps1 = _closed_loop_qps(workers=1)
    qps2 = _closed_loop_qps(workers=2)
    # the acceptance bar is ">= baseline"; a model-bound stub with two
    # workers should land near 2x, so 1.4x keeps the guard meaningful
    # while riding out CI scheduling noise
    assert qps2 >= qps1 * 1.4, (qps1, qps2)


def _scheduler_sweep(scheduler: str, clients: int,
                     duration_s: float = 2.0):
    """Closed-loop (QPS, p50_ms) through a model-bound stub under the
    given scheduler.  batch_size > clients so the window batcher can
    never fill a batch — its ``batch_timeout_ms`` tail is pure latency
    the continuous scheduler does not pay."""
    lat = []
    with ClusterServing(_BusyModel(0.01), batch_size=8,
                        batch_timeout_ms=20, inference_workers=2,
                        scheduler=scheduler) as srv:
        deadline = time.monotonic() + duration_s

        def client(i):
            iq = InputQueue(srv.host, srv.port)
            oq = OutputQueue(input_queue=iq)
            while time.monotonic() < deadline:
                t0 = time.monotonic()
                uid = iq.enqueue(f"c{i}", t=np.ones((4,), np.float32))
                if oq.query(uid, timeout=30.0) is not None:
                    lat.append(time.monotonic() - t0)
            iq.close()

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(clients)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        wall = time.monotonic() - t0
    ms = sorted(t * 1000.0 for t in lat)
    return len(lat) / wall, ms[len(ms) // 2]


def test_continuous_scheduler_meets_window_throughput_at_saturation():
    """4 closed-loop clients against batch_size=8: the window batcher
    waits out its 20 ms timeout every round (the batch can never fill),
    the continuous scheduler dispatches the moment a worker frees — so
    continuous must at least MATCH window throughput (it should far
    exceed it in this regime)."""
    qps_w, _ = _scheduler_sweep("window", clients=4)
    qps_c, _ = _scheduler_sweep("continuous", clients=4)
    assert qps_c >= qps_w, (qps_w, qps_c)


def test_continuous_scheduler_cuts_p50_at_light_load():
    """A lone client's request has nothing to batch with: the window
    scheduler still holds the batch open for ``batch_timeout_ms``; the
    continuous scheduler's p50 must come in clearly below it."""
    _, p50_w = _scheduler_sweep("window", clients=1)
    _, p50_c = _scheduler_sweep("continuous", clients=1)
    assert p50_w >= 20.0, p50_w  # the tail really bit the baseline
    assert p50_c < p50_w * 0.8, (p50_w, p50_c)


def test_prefetch_cuts_data_wait_on_throttled_feed():
    """feed.stall throttles every batch by 4 ms; with prefetch=2 the
    stall overlaps the (heavier) train step, so the loop's measured
    data-wait p50 must drop versus the inline prefetch=0 baseline."""
    init_orca_context("local")
    rng = np.random.default_rng(0)
    x = rng.normal(size=(2048, 256)).astype(np.float32)
    y = rng.normal(size=(2048, 1)).astype(np.float32)

    def wait_p50(prefetch: int) -> float:
        est = Estimator.from_keras(
            nn.Sequential([nn.Dense(512, activation="relu"),
                           nn.Dense(512, activation="relu"),
                           nn.Dense(1)]),
            loss="mse", learning_rate=1e-3, seed=0)
        est.fit((x, y), epochs=1, batch_size=256, verbose=False,
                prefetch=prefetch)  # warm the compile outside the clock
        metrics.get_registry().reset()
        with faults.get_registry().armed("feed.stall", delay=0.004):
            est.fit((x, y), epochs=2, batch_size=256, verbose=False,
                    prefetch=prefetch)
        snap = metrics.get_registry().snapshot()
        return snap["train.data_wait_ms"]["p50"]

    inline = wait_p50(prefetch=0)
    overlapped = wait_p50(prefetch=2)
    assert inline >= 2.0, inline  # the throttle really bit the baseline
    assert overlapped < inline * 0.6, (inline, overlapped)


def _gil_bound_decode(i, rng=None):
    """Pure-Python arithmetic (~1 ms): holds the GIL for its whole
    duration, so N decode THREADS still progress at ~1 core while N
    decode PROCESSES progress at ~N cores."""
    acc = 0
    for k in range(25000):
        acc = (acc + i * 1103515245 + k) & 0x7FFFFFFF
    return {"x": np.full((16,), float(acc % 997), np.float32)}


def test_process_feed_doubles_threaded_on_gil_bound_decoder():
    """ISSUE 7 perf guard: with a GIL-bound decoder and 4 workers, the
    shm-pool process backend must deliver >= 2x the threaded backend's
    feed-only throughput.  Needs real cores to demonstrate parallelism —
    on a 1-2 core CI host both backends serialize on the same silicon,
    so the guard skips rather than asserting physics it can't observe."""
    from analytics_zoo_tpu.data import StreamingDataFeed
    from analytics_zoo_tpu.data import shm_pool
    if (os.cpu_count() or 1) < 4:
        pytest.skip(f"needs >= 4 cores to show process-vs-thread scaling "
                    f"(host has {os.cpu_count()})")
    if not shm_pool.available():
        pytest.skip("shared_memory/fork unavailable")
    mesh = init_orca_context("local")
    n_batches, batch, workers = 48, 32, 4

    def feed_rate(backend: str) -> float:
        feed = StreamingDataFeed(
            num_samples=(n_batches + workers + 6) * batch,
            load_sample=_gil_bound_decode, batch_size=batch,
            shuffle=False, num_workers=workers, prefetch_batches=4,
            workers=backend)
        it = feed.epoch(mesh, 0, place=False)
        for _ in range(workers + 4):     # spin-up + pre-staged drain
            next(it)
        t0 = time.monotonic()
        for _ in range(n_batches):
            next(it)
        dt = time.monotonic() - t0
        it.close()
        return n_batches * batch / dt

    threaded = feed_rate("thread")
    process = feed_rate("process")
    assert process >= 2.0 * threaded, (threaded, process)


# -- sharded embedding engine (ISSUE 11) --------------------------------------

def _zipf_requests(n_req, k, users, items, a=1.5, seed=0):
    """[n_req, 1 + k] request rows ([user | k candidate items]) with
    zipf-skewed ids — the hot-head traffic shape recsys serving sees."""
    rng = np.random.default_rng(seed)
    u = np.minimum(rng.zipf(a, n_req), users) - 1
    it = np.minimum(rng.zipf(a, (n_req, k)), items) - 1
    return np.concatenate([u[:, None], it], axis=1).astype(np.int64)


def _recsys_adapter(cache, users=4096, items=2048, dim=16, seed=0):
    import jax
    import analytics_zoo_tpu.nn as znn
    from analytics_zoo_tpu.serving import (CachedEmbeddingModel,
                                           InferenceModel)
    init_orca_context("local")
    rng = np.random.default_rng(seed)
    tables = {"user_embed": rng.normal(size=(users, dim)).astype(np.float32),
              "item_embed": rng.normal(size=(items, dim)).astype(np.float32)}
    tail = znn.Sequential([znn.Dense(2)])
    tv = tail.init(jax.random.PRNGKey(0),
                   np.zeros((1, 2 * dim), np.float32))
    im = InferenceModel().load(tail, tv)
    return CachedEmbeddingModel(tables,
                                [("user_embed", "user"),
                                 ("item_embed", "item")],
                                im, cache=cache)


def test_deduped_gather_moves_4x_fewer_rows_on_zipf():
    """The tentpole bandwidth win, asserted from the metrics registry:
    on zipf traffic the deduped gather must touch >= 4x fewer embedding
    rows (and bytes) than a per-example naive gather would."""
    reg = metrics.get_registry()
    adapter = _recsys_adapter(cache=None)
    for req in _zipf_requests(256, k=20, users=4096,
                              items=2048).reshape(8, 32, 21):
        adapter.predict(req)
    snap = reg.snapshot()
    ratio = snap["embed.gather_rows_naive"] / snap["embed.gather_rows"]
    byte_ratio = (snap["embed.gather_bytes_naive"]
                  / snap["embed.gather_bytes"])
    assert ratio >= 4.0, ratio
    assert byte_ratio >= 4.0, byte_ratio


def test_hot_row_cache_cuts_serving_p50_on_repeated_trace():
    """Cache on vs off over the same repeated-user closed-loop trace:
    the hot path must answer from host memory (hit rate asserted from
    the registry) and land a lower client-observed p50 than the
    device-gather-every-time baseline."""
    from analytics_zoo_tpu.serving import EmbedCache

    def p50_ms(cache):
        reg = metrics.get_registry()
        reg.reset()
        adapter = _recsys_adapter(cache=cache)
        reqs = _zipf_requests(16, k=20, users=4096, items=2048, a=2.0)
        lat = []
        with ClusterServing(adapter, batch_size=4,
                            batch_timeout_ms=1) as srv:
            iq = InputQueue(srv.host, srv.port)
            oq = OutputQueue(input_queue=iq)
            for i in range(200):
                row = reqs[i % len(reqs)]
                t0 = time.perf_counter()
                uid = iq.enqueue(f"r{i}", t=row)
                assert oq.query(uid, timeout=30.0) is not None
                lat.append((time.perf_counter() - t0) * 1000.0)
            iq.close()
        snap = reg.snapshot()
        lat = sorted(lat[20:])  # drop warmup (jit + cold cache fills)
        return lat[len(lat) // 2], snap

    p50_off, _ = p50_ms(cache=None)
    p50_on, snap = p50_ms(cache=EmbedCache(capacity=100_000))
    hits, misses = snap["embed.cache_hits"], snap["embed.cache_misses"]
    assert hits / (hits + misses) > 0.9, (hits, misses)
    assert p50_on < p50_off, (p50_on, p50_off)
