"""Foreign-model import (VERDICT r1 missing #3): Net.load_torch /
Net.load_tf, differential-tested against the source framework — the
reference's TFNetSpec/TorchNetSpec pattern (SURVEY.md §4.4).
"""

import numpy as np
import pytest

from analytics_zoo_tpu.core import init_orca_context
from analytics_zoo_tpu.models import ForeignNet, Net

torch = pytest.importorskip("torch")


def _apply(net: ForeignNet, x: np.ndarray) -> np.ndarray:
    variables = net.init(__import__("jax").random.PRNGKey(0), x)
    out, _ = net.apply(variables, x)
    return np.asarray(out)


# -- torch --------------------------------------------------------------------

def test_load_torch_mlp_differential():
    init_orca_context("local")
    tm = torch.nn.Sequential(
        torch.nn.Linear(8, 16), torch.nn.ReLU(),
        torch.nn.LayerNorm(16),
        torch.nn.Linear(16, 4), torch.nn.Tanh())
    x = np.random.default_rng(0).normal(size=(5, 8)).astype(np.float32)
    net = Net.load_torch(tm, x)
    with torch.no_grad():
        want = tm(torch.as_tensor(x)).numpy()
    np.testing.assert_allclose(_apply(net, x), want, atol=1e-5)


def test_load_torch_convnet_differential():
    """Conv → BN → pool → flatten → linear: NCHW in, including the
    Flatten/Linear weight reorder into NHWC order."""
    init_orca_context("local")
    tm = torch.nn.Sequential(
        torch.nn.Conv2d(3, 6, 3, padding=1), torch.nn.ReLU(),
        torch.nn.BatchNorm2d(6),
        torch.nn.MaxPool2d(2),
        torch.nn.Conv2d(6, 4, 3),            # valid padding
        torch.nn.Flatten(),
        torch.nn.Linear(4 * 5 * 5, 10)).eval()
    # make BN stats non-trivial
    with torch.no_grad():
        tm[2].running_mean.uniform_(-0.5, 0.5)
        tm[2].running_var.uniform_(0.5, 1.5)
    x = np.random.default_rng(1).normal(size=(4, 3, 14, 14)) \
        .astype(np.float32)
    net = Net.load_torch(tm, x)
    assert net.nchw_input
    with torch.no_grad():
        want = tm(torch.as_tensor(x)).numpy()
    np.testing.assert_allclose(_apply(net, x), want, atol=1e-4)


def test_load_torch_torchscript_file(tmp_path):
    init_orca_context("local")
    tm = torch.nn.Sequential(torch.nn.Linear(4, 3), torch.nn.Sigmoid())
    path = str(tmp_path / "m.pt")
    torch.jit.script(tm).save(path)
    x = np.random.default_rng(2).normal(size=(3, 4)).astype(np.float32)
    net = Net.load_torch(path, x)
    with torch.no_grad():
        want = tm(torch.as_tensor(x)).numpy()
    np.testing.assert_allclose(_apply(net, x), want, atol=1e-5)


def test_load_torch_unsupported_layer_names_escape_hatch():
    init_orca_context("local")
    tm = torch.nn.Sequential(torch.nn.Linear(4, 4),
                             torch.nn.MultiheadAttention(4, 2))
    with pytest.raises(NotImplementedError, match="escape hatch"):
        Net.load_torch(tm, np.zeros((2, 4), np.float32))


def test_torch_params_to_tree():
    tm = torch.nn.Sequential(torch.nn.Linear(3, 2),
                             torch.nn.BatchNorm1d(2))
    tree = Net.torch_params_to_tree(tm)
    assert tree["0.weight"].shape == (2, 3)
    assert "1.running_mean" in tree


def test_load_torch_finetunes_through_estimator():
    """The capability JNI bridges never had: imported weights, fine-tuned."""
    from analytics_zoo_tpu.orca.learn import Estimator
    init_orca_context("local")
    tm = torch.nn.Sequential(torch.nn.Linear(6, 8), torch.nn.ReLU(),
                             torch.nn.Linear(8, 2))
    rng = np.random.default_rng(3)
    x = rng.normal(size=(32, 6)).astype(np.float32)
    y = rng.integers(0, 2, 32).astype(np.int32)
    net = Net.load_torch(tm, x[:2])
    est = Estimator.from_keras(net, loss="sparse_categorical_crossentropy",
                               learning_rate=1e-2)
    before = _apply(net, x[:4])
    with torch.no_grad():
        np.testing.assert_allclose(before, tm(torch.as_tensor(x[:4])).numpy(),
                                   atol=1e-5)  # starts AT the torch weights
    hist = est.fit((x, y), epochs=3, batch_size=16, verbose=False)
    assert hist["loss"][-1] < hist["loss"][0]  # and actually trains


def test_load_torch_head_with_dropout_between_flatten_and_linear():
    """The kernel reorder must survive order-preserving layers between
    Flatten and Linear (regression: it used to apply only when Linear
    immediately followed Flatten)."""
    init_orca_context("local")
    tm = torch.nn.Sequential(
        torch.nn.Conv2d(2, 3, 3), torch.nn.Flatten(),
        torch.nn.Dropout(0.5), torch.nn.ReLU(),
        torch.nn.Linear(3 * 4 * 4, 5)).eval()
    x = np.random.default_rng(4).normal(size=(2, 2, 6, 6)).astype(np.float32)
    net = Net.load_torch(tm, x)
    with torch.no_grad():
        want = tm(torch.as_tensor(x)).numpy()
    np.testing.assert_allclose(_apply(net, x), want, atol=1e-5)


def test_load_torch_conv_ending_net_keeps_torch_layout():
    """A net ending in conv features must hand back NCHW like the source."""
    init_orca_context("local")
    tm = torch.nn.Sequential(torch.nn.Conv2d(3, 5, 3), torch.nn.ReLU())
    x = np.random.default_rng(5).normal(size=(2, 3, 8, 8)).astype(np.float32)
    net = Net.load_torch(tm, x)
    out = _apply(net, x)
    with torch.no_grad():
        want = tm(torch.as_tensor(x)).numpy()
    assert out.shape == want.shape == (2, 5, 6, 6)
    np.testing.assert_allclose(out, want, atol=1e-5)


def test_load_torch_exact_gelu():
    """torch GELU defaults to erf-exact; the conversion must not swap in
    the tanh approximation."""
    init_orca_context("local")
    tm = torch.nn.Sequential(torch.nn.Linear(16, 16), torch.nn.GELU())
    x = np.random.default_rng(6).normal(size=(8, 16)).astype(np.float32)
    net = Net.load_torch(tm, x)
    with torch.no_grad():
        want = tm(torch.as_tensor(x)).numpy()
    np.testing.assert_allclose(_apply(net, x), want, atol=1e-6)


# -- tf/keras -----------------------------------------------------------------


def test_load_tf_mlp_differential():
    tf = pytest.importorskip("tensorflow")
    init_orca_context("local")
    km = tf.keras.Sequential([
        tf.keras.layers.Input((8,)),
        tf.keras.layers.Dense(16, activation="relu"),
        tf.keras.layers.LayerNormalization(),
        tf.keras.layers.Dense(4, activation="softmax")])
    x = np.random.default_rng(0).normal(size=(5, 8)).astype(np.float32)
    net = Net.load_tf(km)
    want = km(x).numpy()
    np.testing.assert_allclose(_apply(net, x), want, atol=1e-5)


def test_load_tf_convnet_differential():
    tf = pytest.importorskip("tensorflow")
    init_orca_context("local")
    km = tf.keras.Sequential([
        tf.keras.layers.Input((12, 12, 3)),
        tf.keras.layers.Conv2D(6, 3, padding="same", activation="relu"),
        tf.keras.layers.BatchNormalization(),
        tf.keras.layers.MaxPooling2D(2),
        tf.keras.layers.Conv2D(4, 3, padding="valid"),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(10)])
    # non-trivial BN stats
    bn = km.layers[1]
    w = bn.get_weights()
    rng = np.random.default_rng(1)
    w[2] = rng.normal(0, 0.3, w[2].shape).astype(np.float32)
    w[3] = rng.uniform(0.5, 1.5, w[3].shape).astype(np.float32)
    bn.set_weights(w)
    x = rng.normal(size=(4, 12, 12, 3)).astype(np.float32)
    net = Net.load_tf(km)
    want = km(x, training=False).numpy()
    np.testing.assert_allclose(_apply(net, x), want, atol=1e-4)


def test_load_tf_from_saved_file(tmp_path):
    tf = pytest.importorskip("tensorflow")
    init_orca_context("local")
    km = tf.keras.Sequential([
        tf.keras.layers.Input((6,)),
        tf.keras.layers.Dense(3, activation="tanh")])
    path = str(tmp_path / "model.keras")
    km.save(path)
    x = np.random.default_rng(2).normal(size=(3, 6)).astype(np.float32)
    net = Net.load_tf(path)
    np.testing.assert_allclose(_apply(net, x), km(x).numpy(), atol=1e-5)


def test_load_tf_unsupported_layer_names_escape_hatch():
    tf = pytest.importorskip("tensorflow")
    init_orca_context("local")
    km = tf.keras.Sequential([
        tf.keras.layers.Input((4, 8)),
        tf.keras.layers.LSTM(4)])
    with pytest.raises(NotImplementedError, match="escape hatch"):
        Net.load_tf(km)


def test_load_bigdl_documented_drop():
    with pytest.raises(NotImplementedError, match="consciously dropped"):
        Net.load_bigdl("whatever")


# -- graph-structured conversion (VERDICT r2 missing #4) ----------------------

def _resnet18_torch():
    """torchvision-style ResNet-18 (BasicBlock, downsample 1x1 convs,
    padded stem + maxpool, residual adds) — torchvision itself is not in
    the image, so the structure is rebuilt faithfully here."""
    tnn = torch.nn

    class BasicBlock(tnn.Module):
        def __init__(self, cin, cout, stride=1):
            super().__init__()
            self.conv1 = tnn.Conv2d(cin, cout, 3, stride=stride, padding=1,
                                    bias=False)
            self.bn1 = tnn.BatchNorm2d(cout)
            self.relu = tnn.ReLU(inplace=True)
            self.conv2 = tnn.Conv2d(cout, cout, 3, padding=1, bias=False)
            self.bn2 = tnn.BatchNorm2d(cout)
            self.downsample = (
                tnn.Sequential(tnn.Conv2d(cin, cout, 1, stride=stride,
                                          bias=False),
                               tnn.BatchNorm2d(cout))
                if (stride != 1 or cin != cout) else None)

        def forward(self, x):
            identity = x if self.downsample is None else self.downsample(x)
            out = self.relu(self.bn1(self.conv1(x)))
            out = self.bn2(self.conv2(out))
            out += identity
            return self.relu(out)

    def layer(cin, cout, stride):
        return tnn.Sequential(BasicBlock(cin, cout, stride),
                              BasicBlock(cout, cout))

    class ResNet18(tnn.Module):
        def __init__(self, w=8, classes=10):
            super().__init__()
            self.conv1 = tnn.Conv2d(3, w, 7, stride=2, padding=3, bias=False)
            self.bn1 = tnn.BatchNorm2d(w)
            self.relu = tnn.ReLU(inplace=True)
            self.maxpool = tnn.MaxPool2d(3, stride=2, padding=1)
            self.layer1 = layer(w, w, 1)
            self.layer2 = layer(w, 2 * w, 2)
            self.layer3 = layer(2 * w, 4 * w, 2)
            self.layer4 = layer(4 * w, 8 * w, 2)
            self.avgpool = tnn.AdaptiveAvgPool2d(1)
            self.fc = tnn.Linear(8 * w, classes)

        def forward(self, x):
            x = self.relu(self.bn1(self.conv1(x)))
            x = self.maxpool(x)
            x = self.layer1(x)
            x = self.layer2(x)
            x = self.layer3(x)
            x = self.layer4(x)
            x = self.avgpool(x)
            x = torch.flatten(x, 1)
            return self.fc(x)

    m = ResNet18().eval()
    # non-trivial BN running stats so the differential test has teeth
    g = torch.Generator().manual_seed(7)
    for mod in m.modules():
        if isinstance(mod, torch.nn.BatchNorm2d):
            mod.running_mean.uniform_(-0.5, 0.5, generator=g)
            mod.running_var.uniform_(0.5, 2.0, generator=g)
    return m


def test_load_torch_resnet18_graph_differential():
    """Residual/branching torch module (the VERDICT r2 'graph-structured
    foreign import' case): converts via torch.fx and matches torch."""
    init_orca_context("local")
    m = _resnet18_torch()
    x = np.random.default_rng(0).normal(size=(2, 3, 64, 64)).astype(
        np.float32)
    with torch.no_grad():
        want = m(torch.as_tensor(x)).numpy()
    net = Net.load_torch(m, x)
    from analytics_zoo_tpu.models.net import ForeignGraphNet
    assert isinstance(net, ForeignGraphNet)
    np.testing.assert_allclose(_apply(net, x), want, atol=5e-4)


def test_load_torch_graph_finetunes_through_estimator():
    """The converted graph net trains like any native model."""
    init_orca_context("local")
    from analytics_zoo_tpu.orca.learn import Estimator
    m = _resnet18_torch()
    x = np.random.default_rng(0).normal(size=(8, 3, 32, 32)).astype(
        np.float32)
    y = np.random.default_rng(1).integers(0, 10, 8).astype(np.int32)
    net = Net.load_torch(m, x)
    est = Estimator.from_keras(net, loss="sparse_categorical_crossentropy",
                               optimizer="adam", learning_rate=1e-3)
    hist = est.fit((x, y), epochs=2, batch_size=8, verbose=False)
    assert hist["loss"][-1] < hist["loss"][0]


def test_load_tf_functional_skip_differential():
    """Functional keras model with a skip connection and a concat merge."""
    tf = pytest.importorskip("tensorflow")
    keras = tf.keras
    init_orca_context("local")
    inp = keras.Input((12, 12, 3))
    h = keras.layers.Conv2D(6, 3, padding="same", activation="relu",
                            name="c1")(inp)
    b = keras.layers.Conv2D(6, 3, padding="same", name="c2")(h)
    b = keras.layers.BatchNormalization(name="bn")(b)
    s = keras.layers.Add(name="skip")([h, b])
    s = keras.layers.ReLU(name="relu")(s)
    p = keras.layers.GlobalAveragePooling2D(name="gap")(s)
    d1 = keras.layers.Dense(8, activation="relu", name="d1")(p)
    d2 = keras.layers.Dense(8, name="d2")(p)
    cat = keras.layers.Concatenate(name="cat")([d1, d2])
    out = keras.layers.Dense(4, name="head")(cat)
    model = keras.Model(inp, out)
    bn = model.get_layer("bn")
    w = bn.get_weights()
    w[2] = np.random.default_rng(0).normal(0, 0.5, w[2].shape).astype(
        np.float32)
    w[3] = np.abs(np.random.default_rng(1).normal(1.0, 0.3, w[3].shape)
                  ).astype(np.float32)
    bn.set_weights(w)
    x = np.random.default_rng(2).normal(size=(4, 12, 12, 3)).astype(
        np.float32)
    want = model(x, training=False).numpy()
    net = Net.load_tf(model)
    from analytics_zoo_tpu.models.net import ForeignGraphNet
    assert isinstance(net, ForeignGraphNet)
    np.testing.assert_allclose(_apply(net, x), want, atol=1e-4)


def test_load_tf_functional_shared_layer_names_escape_hatch():
    tf = pytest.importorskip("tensorflow")
    keras = tf.keras
    init_orca_context("local")
    inp = keras.Input((4,))
    shared = keras.layers.Dense(4, name="shared")
    out = keras.layers.Add()([shared(inp), shared(shared(inp))])
    model = keras.Model(inp, out)
    with pytest.raises(NotImplementedError, match="[Ss]hared"):
        Net.load_tf(model)


def test_estimator_from_torch_reference_style_script():
    """A reference-style Orca PyTorch script: build torch model, call
    Estimator.from_torch, fit/evaluate/predict — only the import line
    differs from the reference's pyzoo examples (VERDICT r2 weak #5)."""
    init_orca_context("local")
    from analytics_zoo_tpu.orca.learn import Estimator  # the changed import

    model = torch.nn.Sequential(
        torch.nn.Linear(8, 32), torch.nn.ReLU(),
        torch.nn.Linear(32, 2))
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 8)).astype(np.float32)
    y = (x.sum(1) > 0).astype(np.int32)

    est = Estimator.from_torch(model=model, loss="sparse_categorical_crossentropy",
                               optimizer="adam", learning_rate=5e-3,
                               metrics=["accuracy"], example_input=x[:4])
    hist = est.fit((x, y), epochs=8, batch_size=32, verbose=False)
    assert hist["loss"][-1] < hist["loss"][0]
    res = est.evaluate((x, y), batch_size=32)
    assert res["accuracy"] > 0.7
    pred = est.predict(x[:8], batch_size=8)
    assert np.asarray(pred).shape == (8, 2)


def test_estimator_from_graph_keras_model():
    tf = pytest.importorskip("tensorflow")
    init_orca_context("local")
    from analytics_zoo_tpu.orca.learn import Estimator
    keras = tf.keras
    m = keras.Sequential([keras.layers.Input((6,)),
                          keras.layers.Dense(16, activation="relu"),
                          keras.layers.Dense(2)])
    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 6)).astype(np.float32)
    y = rng.integers(0, 2, 32).astype(np.int32)
    est = Estimator.from_graph(m, loss="sparse_categorical_crossentropy",
                               optimizer="adam", learning_rate=1e-3)
    hist = est.fit((x, y), epochs=2, batch_size=16, verbose=False)
    assert len(hist["loss"]) == 2


def test_fx_constant_first_binop_and_rsub():
    """Regression (r3 review): '1.0 - x' (constant-first binop) must not
    crash conversion, and rsub must compute other - input."""
    init_orca_context("local")

    class M(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.fc = torch.nn.Linear(4, 4)

        def forward(self, x):
            h = self.fc(x)
            g = 1.0 - torch.sigmoid(h)   # constant on the left
            return torch.rsub(g, 2.0)    # 2.0 - g

    m = M().eval()
    x = np.random.default_rng(0).normal(size=(3, 4)).astype(np.float32)
    with torch.no_grad():
        want = m(torch.as_tensor(x)).numpy()
    net = Net.load_torch_graph(m, x)
    np.testing.assert_allclose(_apply(net, x), want, atol=1e-5)


def test_fx_4d_constant_buffer_transposed_to_nhwc():
    """Regression (r3 review): a (1,C,1,1) buffer multiplied into feature
    maps must be NHWC-transposed at the conversion boundary."""
    init_orca_context("local")

    class M(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.conv = torch.nn.Conv2d(3, 6, 3, padding=1)
            self.register_buffer("scale",
                                 torch.arange(1.0, 7.0).view(1, 6, 1, 1))

        def forward(self, x):
            return self.conv(x) * self.scale

    m = M().eval()
    x = np.random.default_rng(1).normal(size=(2, 3, 6, 6)).astype(
        np.float32)
    with torch.no_grad():
        want = m(torch.as_tensor(x)).numpy()
    net = Net.load_torch_graph(m, x)
    np.testing.assert_allclose(_apply(net, x), want, atol=1e-5)


def test_fx_module_relu_between_flatten_and_linear_reorders_kernel():
    """Regression (r3 review): an nn.ReLU MODULE between Flatten and
    Linear must still trigger the NCHW->NHWC kernel reorder."""
    init_orca_context("local")

    class M(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.conv = torch.nn.Conv2d(3, 4, 3, padding=1)
            self.flat = torch.nn.Flatten()
            self.act = torch.nn.ReLU()
            self.fc = torch.nn.Linear(4 * 5 * 5, 2)

        def forward(self, x):
            h = self.conv(x)
            h = h + h  # binop node: forces the fx graph path
            return self.fc(self.act(self.flat(h)))

    m = M().eval()
    x = np.random.default_rng(2).normal(size=(2, 3, 5, 5)).astype(
        np.float32)
    with torch.no_grad():
        want = m(torch.as_tensor(x)).numpy()
    net = Net.load_torch_graph(m, x)
    np.testing.assert_allclose(_apply(net, x), want, atol=1e-5)


def test_fx_functional_pool_with_padding_and_ceil_mode():
    """Regression (r3 review): F.max_pool2d padding converts exactly;
    ceil_mode raises the documented error."""
    import torch.nn.functional as F
    init_orca_context("local")

    class Pad(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.conv = torch.nn.Conv2d(3, 4, 3, padding=1)

        def forward(self, x):
            return F.max_pool2d(self.conv(x) + 0.0, 3, 2, 1)

    m = Pad().eval()
    x = np.random.default_rng(3).normal(size=(2, 3, 9, 9)).astype(
        np.float32)
    with torch.no_grad():
        want = m(torch.as_tensor(x)).numpy()
    net = Net.load_torch_graph(m, x)
    np.testing.assert_allclose(_apply(net, x), want, atol=1e-5)

    class Ceil(Pad):
        def forward(self, x):
            return F.max_pool2d(self.conv(x) + 0.0, 2, 2, ceil_mode=True)

    with pytest.raises(NotImplementedError, match="ceil_mode"):
        Net.load_torch_graph(Ceil().eval(), x)


def test_fx_view_size_flatten_pattern():
    """Regression (r3 review): the classic x.view(x.size(0), -1) flatten
    converts (a call_method 'size' node precedes the view)."""
    init_orca_context("local")

    class M(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.conv = torch.nn.Conv2d(3, 4, 3, padding=1)
            self.fc = torch.nn.Linear(4 * 5 * 5, 2)

        def forward(self, x):
            h = self.conv(x)
            h = h + h
            return self.fc(h.view(h.size(0), -1))

    m = M().eval()
    x = np.random.default_rng(0).normal(size=(2, 3, 5, 5)).astype(
        np.float32)
    with torch.no_grad():
        want = m(torch.as_tensor(x)).numpy()
    net = Net.load_torch_graph(m, x)
    np.testing.assert_allclose(_apply(net, x), want, atol=1e-5)


def test_fx_softmax_axis_mapping_on_4d():
    """Regression (r3 review): softmax over any NCHW dim maps to the
    right NHWC axis."""
    init_orca_context("local")

    class M(torch.nn.Module):
        def __init__(self, dim):
            super().__init__()
            self.conv = torch.nn.Conv2d(3, 4, 1)
            self.dim = dim

        def forward(self, x):
            h = self.conv(x)
            return torch.softmax(h + h, dim=self.dim)

    x = np.random.default_rng(1).normal(size=(2, 3, 4, 5)).astype(
        np.float32)
    for dim in (1, 2, 3):
        m = M(dim).eval()
        with torch.no_grad():
            want = m(torch.as_tensor(x)).numpy()
        net = Net.load_torch_graph(m, x)
        np.testing.assert_allclose(_apply(net, x), want, atol=1e-5,
                                   err_msg=f"dim={dim}")


def test_fx_cat_of_flattened_branches_raises():
    """Regression (r3 review): cat of two flattened NCHW maps into a
    Linear cannot be silently mis-ordered — it must raise."""
    init_orca_context("local")

    class M(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.c1 = torch.nn.Conv2d(3, 4, 1)
            self.c2 = torch.nn.Conv2d(3, 4, 1)
            self.fc = torch.nn.Linear(2 * 4 * 4 * 4, 2)

        def forward(self, x):
            a = torch.flatten(self.c1(x), 1)
            b = torch.flatten(self.c2(x), 1)
            return self.fc(torch.cat([a, b], dim=1))

    x = np.zeros((2, 3, 4, 4), np.float32)
    with pytest.raises(NotImplementedError, match="escape hatch"):
        Net.load_torch_graph(M().eval(), x)


def test_load_tf_functional_input_order_from_spec():
    """Regression (r3 review): multi-input binding follows
    Model(inputs=[a, b]) order, not layer-creation order."""
    tf = pytest.importorskip("tensorflow")
    keras = tf.keras
    init_orca_context("local")
    # create b BEFORE a so creation order disagrees with inputs=[a, b]
    b = keras.Input((3,), name="in_b")
    a = keras.Input((3,), name="in_a")
    out = keras.layers.Subtract(name="sub")([
        keras.layers.Dense(3, name="da")(a),
        keras.layers.Dense(3, name="db")(b)])
    model = keras.Model([a, b], out)
    xa = np.random.default_rng(0).normal(size=(2, 3)).astype(np.float32)
    xb = np.random.default_rng(1).normal(size=(2, 3)).astype(np.float32)
    want = model([xa, xb], training=False).numpy()
    net = Net.load_tf(model)
    import jax
    variables = net.init(jax.random.PRNGKey(0), xa, xb)
    got, _ = net.apply(variables, xa, xb)
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-5)


def test_fx_densenet_style_channel_concat():
    """DenseNet-style 4-D channel concats (cat dim=1 on feature maps)
    convert and match torch."""
    init_orca_context("local")

    class DenseBlock(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.c1 = torch.nn.Conv2d(3, 4, 3, padding=1)
            self.c2 = torch.nn.Conv2d(7, 4, 3, padding=1)
            self.pool = torch.nn.AdaptiveAvgPool2d(1)
            self.fc = torch.nn.Linear(11, 2)

        def forward(self, x):
            h1 = torch.relu(self.c1(x))
            x1 = torch.cat([x, h1], dim=1)          # 3 + 4 = 7 channels
            h2 = torch.relu(self.c2(x1))
            x2 = torch.cat([x1, h2], dim=1)         # 7 + 4 = 11
            p = self.pool(x2)
            return self.fc(torch.flatten(p, 1))

    m = DenseBlock().eval()
    x = np.random.default_rng(4).normal(size=(2, 3, 8, 8)).astype(
        np.float32)
    with torch.no_grad():
        want = m(torch.as_tensor(x)).numpy()
    net = Net.load_torch_graph(m, x)
    np.testing.assert_allclose(_apply(net, x), want, atol=1e-5)


def test_load_torch_rejects_flattened_plus_constant_chain():
    """Regression (r4 review): a non-scalar constant reaching an
    elementwise op with a flattened NCHW map must raise even when routed
    through intermediate ops (here: buffer * 2.0), not only as a direct
    get_attr operand — the element orders differ silently otherwise."""
    init_orca_context("local")

    class M(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.conv = torch.nn.Conv2d(2, 3, 3)
            self.register_buffer("c", torch.randn(3 * 4 * 4))

        def forward(self, x):
            f = torch.flatten(self.conv(x), 1)
            return f + self.c * 2.0

    x = np.random.default_rng(0).normal(size=(2, 2, 6, 6)).astype(
        np.float32)
    with pytest.raises(NotImplementedError, match="constant"):
        Net.load_torch(M().eval(), x)


def test_load_keras_named_entry_point():
    """Reference parity (SURVEY.md §2.3 Net loaders): ``Net.load_keras``
    must exist as a named entry point, routing to the tf.keras
    conversion path."""
    tf = pytest.importorskip("tensorflow")
    init_orca_context("local")
    km = tf.keras.Sequential([
        tf.keras.layers.Input((6,)),
        tf.keras.layers.Dense(4, activation="relu"),
        tf.keras.layers.Dense(2)])
    x = np.random.default_rng(1).normal(size=(3, 6)).astype(np.float32)
    net = Net.load_keras(km)
    np.testing.assert_allclose(_apply(net, x), km(x).numpy(), atol=1e-5)


def test_load_keras_json_def_plus_weights(tmp_path):
    """Reference call form: ``Net.load_keras(def_json, weights_h5)`` —
    architecture JSON + separate weights file."""
    tf = pytest.importorskip("tensorflow")
    init_orca_context("local")
    km = tf.keras.Sequential([
        tf.keras.layers.Input((5,)),
        tf.keras.layers.Dense(3, activation="tanh"),
        tf.keras.layers.Dense(2)])
    d = tmp_path / "def.json"
    w = tmp_path / "weights.weights.h5"
    d.write_text(km.to_json())
    km.save_weights(str(w))
    x = np.random.default_rng(2).normal(size=(4, 5)).astype(np.float32)
    net = Net.load_keras(str(d), str(w))
    np.testing.assert_allclose(_apply(net, x), km(x).numpy(), atol=1e-5)
