"""Foreign-model import (VERDICT r1 missing #3): Net.load_torch /
Net.load_tf, differential-tested against the source framework — the
reference's TFNetSpec/TorchNetSpec pattern (SURVEY.md §4.4).
"""

import numpy as np
import pytest

from analytics_zoo_tpu.core import init_orca_context
from analytics_zoo_tpu.models import ForeignNet, Net

torch = pytest.importorskip("torch")


def _apply(net: ForeignNet, x: np.ndarray) -> np.ndarray:
    variables = net.init(__import__("jax").random.PRNGKey(0), x)
    out, _ = net.apply(variables, x)
    return np.asarray(out)


# -- torch --------------------------------------------------------------------

def test_load_torch_mlp_differential():
    init_orca_context("local")
    tm = torch.nn.Sequential(
        torch.nn.Linear(8, 16), torch.nn.ReLU(),
        torch.nn.LayerNorm(16),
        torch.nn.Linear(16, 4), torch.nn.Tanh())
    x = np.random.default_rng(0).normal(size=(5, 8)).astype(np.float32)
    net = Net.load_torch(tm, x)
    with torch.no_grad():
        want = tm(torch.as_tensor(x)).numpy()
    np.testing.assert_allclose(_apply(net, x), want, atol=1e-5)


def test_load_torch_convnet_differential():
    """Conv → BN → pool → flatten → linear: NCHW in, including the
    Flatten/Linear weight reorder into NHWC order."""
    init_orca_context("local")
    tm = torch.nn.Sequential(
        torch.nn.Conv2d(3, 6, 3, padding=1), torch.nn.ReLU(),
        torch.nn.BatchNorm2d(6),
        torch.nn.MaxPool2d(2),
        torch.nn.Conv2d(6, 4, 3),            # valid padding
        torch.nn.Flatten(),
        torch.nn.Linear(4 * 5 * 5, 10)).eval()
    # make BN stats non-trivial
    with torch.no_grad():
        tm[2].running_mean.uniform_(-0.5, 0.5)
        tm[2].running_var.uniform_(0.5, 1.5)
    x = np.random.default_rng(1).normal(size=(4, 3, 14, 14)) \
        .astype(np.float32)
    net = Net.load_torch(tm, x)
    assert net.nchw_input
    with torch.no_grad():
        want = tm(torch.as_tensor(x)).numpy()
    np.testing.assert_allclose(_apply(net, x), want, atol=1e-4)


def test_load_torch_torchscript_file(tmp_path):
    init_orca_context("local")
    tm = torch.nn.Sequential(torch.nn.Linear(4, 3), torch.nn.Sigmoid())
    path = str(tmp_path / "m.pt")
    torch.jit.script(tm).save(path)
    x = np.random.default_rng(2).normal(size=(3, 4)).astype(np.float32)
    net = Net.load_torch(path, x)
    with torch.no_grad():
        want = tm(torch.as_tensor(x)).numpy()
    np.testing.assert_allclose(_apply(net, x), want, atol=1e-5)


def test_load_torch_unsupported_layer_names_escape_hatch():
    init_orca_context("local")
    tm = torch.nn.Sequential(torch.nn.Linear(4, 4),
                             torch.nn.MultiheadAttention(4, 2))
    with pytest.raises(NotImplementedError, match="escape hatch"):
        Net.load_torch(tm, np.zeros((2, 4), np.float32))


def test_torch_params_to_tree():
    tm = torch.nn.Sequential(torch.nn.Linear(3, 2),
                             torch.nn.BatchNorm1d(2))
    tree = Net.torch_params_to_tree(tm)
    assert tree["0.weight"].shape == (2, 3)
    assert "1.running_mean" in tree


def test_load_torch_finetunes_through_estimator():
    """The capability JNI bridges never had: imported weights, fine-tuned."""
    from analytics_zoo_tpu.orca.learn import Estimator
    init_orca_context("local")
    tm = torch.nn.Sequential(torch.nn.Linear(6, 8), torch.nn.ReLU(),
                             torch.nn.Linear(8, 2))
    rng = np.random.default_rng(3)
    x = rng.normal(size=(32, 6)).astype(np.float32)
    y = rng.integers(0, 2, 32).astype(np.int32)
    net = Net.load_torch(tm, x[:2])
    est = Estimator.from_keras(net, loss="sparse_categorical_crossentropy",
                               learning_rate=1e-2)
    before = _apply(net, x[:4])
    with torch.no_grad():
        np.testing.assert_allclose(before, tm(torch.as_tensor(x[:4])).numpy(),
                                   atol=1e-5)  # starts AT the torch weights
    hist = est.fit((x, y), epochs=3, batch_size=16, verbose=False)
    assert hist["loss"][-1] < hist["loss"][0]  # and actually trains


def test_load_torch_head_with_dropout_between_flatten_and_linear():
    """The kernel reorder must survive order-preserving layers between
    Flatten and Linear (regression: it used to apply only when Linear
    immediately followed Flatten)."""
    init_orca_context("local")
    tm = torch.nn.Sequential(
        torch.nn.Conv2d(2, 3, 3), torch.nn.Flatten(),
        torch.nn.Dropout(0.5), torch.nn.ReLU(),
        torch.nn.Linear(3 * 4 * 4, 5)).eval()
    x = np.random.default_rng(4).normal(size=(2, 2, 6, 6)).astype(np.float32)
    net = Net.load_torch(tm, x)
    with torch.no_grad():
        want = tm(torch.as_tensor(x)).numpy()
    np.testing.assert_allclose(_apply(net, x), want, atol=1e-5)


def test_load_torch_conv_ending_net_keeps_torch_layout():
    """A net ending in conv features must hand back NCHW like the source."""
    init_orca_context("local")
    tm = torch.nn.Sequential(torch.nn.Conv2d(3, 5, 3), torch.nn.ReLU())
    x = np.random.default_rng(5).normal(size=(2, 3, 8, 8)).astype(np.float32)
    net = Net.load_torch(tm, x)
    out = _apply(net, x)
    with torch.no_grad():
        want = tm(torch.as_tensor(x)).numpy()
    assert out.shape == want.shape == (2, 5, 6, 6)
    np.testing.assert_allclose(out, want, atol=1e-5)


def test_load_torch_exact_gelu():
    """torch GELU defaults to erf-exact; the conversion must not swap in
    the tanh approximation."""
    init_orca_context("local")
    tm = torch.nn.Sequential(torch.nn.Linear(16, 16), torch.nn.GELU())
    x = np.random.default_rng(6).normal(size=(8, 16)).astype(np.float32)
    net = Net.load_torch(tm, x)
    with torch.no_grad():
        want = tm(torch.as_tensor(x)).numpy()
    np.testing.assert_allclose(_apply(net, x), want, atol=1e-6)


# -- tf/keras -----------------------------------------------------------------


def test_load_tf_mlp_differential():
    tf = pytest.importorskip("tensorflow")
    init_orca_context("local")
    km = tf.keras.Sequential([
        tf.keras.layers.Input((8,)),
        tf.keras.layers.Dense(16, activation="relu"),
        tf.keras.layers.LayerNormalization(),
        tf.keras.layers.Dense(4, activation="softmax")])
    x = np.random.default_rng(0).normal(size=(5, 8)).astype(np.float32)
    net = Net.load_tf(km)
    want = km(x).numpy()
    np.testing.assert_allclose(_apply(net, x), want, atol=1e-5)


def test_load_tf_convnet_differential():
    tf = pytest.importorskip("tensorflow")
    init_orca_context("local")
    km = tf.keras.Sequential([
        tf.keras.layers.Input((12, 12, 3)),
        tf.keras.layers.Conv2D(6, 3, padding="same", activation="relu"),
        tf.keras.layers.BatchNormalization(),
        tf.keras.layers.MaxPooling2D(2),
        tf.keras.layers.Conv2D(4, 3, padding="valid"),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(10)])
    # non-trivial BN stats
    bn = km.layers[1]
    w = bn.get_weights()
    rng = np.random.default_rng(1)
    w[2] = rng.normal(0, 0.3, w[2].shape).astype(np.float32)
    w[3] = rng.uniform(0.5, 1.5, w[3].shape).astype(np.float32)
    bn.set_weights(w)
    x = rng.normal(size=(4, 12, 12, 3)).astype(np.float32)
    net = Net.load_tf(km)
    want = km(x, training=False).numpy()
    np.testing.assert_allclose(_apply(net, x), want, atol=1e-4)


def test_load_tf_from_saved_file(tmp_path):
    tf = pytest.importorskip("tensorflow")
    init_orca_context("local")
    km = tf.keras.Sequential([
        tf.keras.layers.Input((6,)),
        tf.keras.layers.Dense(3, activation="tanh")])
    path = str(tmp_path / "model.keras")
    km.save(path)
    x = np.random.default_rng(2).normal(size=(3, 6)).astype(np.float32)
    net = Net.load_tf(path)
    np.testing.assert_allclose(_apply(net, x), km(x).numpy(), atol=1e-5)


def test_load_tf_unsupported_layer_names_escape_hatch():
    tf = pytest.importorskip("tensorflow")
    init_orca_context("local")
    km = tf.keras.Sequential([
        tf.keras.layers.Input((4, 8)),
        tf.keras.layers.LSTM(4)])
    with pytest.raises(NotImplementedError, match="escape hatch"):
        Net.load_tf(km)


def test_load_bigdl_documented_drop():
    with pytest.raises(NotImplementedError, match="consciously dropped"):
        Net.load_bigdl("whatever")
