"""Asynchronous checkpoint manager (ISSUE 15, core/ckpt_manager.py):
non-blocking snapshots with explicit in-flight policies, delta
checkpoints for sharded-embedding tables, manifest-driven retention/GC,
and crash-consistent restore — plus the estimator, serving-registry and
CLI integrations."""

import json
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from analytics_zoo_tpu.core import checkpoint as ckpt_io
from analytics_zoo_tpu.core import ckpt_manager as cm
from analytics_zoo_tpu.core import faults as faults_lib
from analytics_zoo_tpu.core import init_orca_context
from analytics_zoo_tpu.core import metrics as metrics_lib


def _tree(table_val=0.0, w_val=1.0, rows=16, dim=4):
    return {"params": {"w": jnp.full((3, 3), w_val),
                       "emb": {"sharded_embeddings":
                               jnp.full((rows, dim), table_val)}},
            "step": jnp.asarray(0)}


TP = "params/emb/sharded_embeddings"


def _assert_trees_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# -- core manager semantics ---------------------------------------------------

def test_full_then_delta_roundtrip_and_verify(tmp_path):
    d = str(tmp_path / "c")
    t = _tree()
    with cm.CheckpointManager(d) as m:
        assert m.save_async(t, step=1)
        m.flush()
        t["params"]["emb"]["sharded_embeddings"] = \
            t["params"]["emb"]["sharded_embeddings"].at[3].set(7.5)
        assert m.save_async(t, step=2, touched={TP: np.array([3])})
        m.flush()
        kinds = [r["kind"] for r in m.generations()]
        assert kinds == ["full", "delta"]
        assert m.verify() == []
        _assert_trees_equal(m.restore(), t)


def test_delta_restore_equals_full_restore_exactly(tmp_path):
    """Base + ordered deltas must reproduce the same bytes a full save
    of the final state does — params, scalars, and embedding rows."""
    da, db = str(tmp_path / "delta"), str(tmp_path / "full")
    t = _tree()
    with cm.CheckpointManager(da) as m:
        m.save(t, step=1)
        for i, step in enumerate(range(2, 5)):
            tbl = t["params"]["emb"]["sharded_embeddings"]
            t["params"]["emb"]["sharded_embeddings"] = \
                tbl.at[i].set(float(step))
            t["params"]["w"] = t["params"]["w"] + 1.0
            t["step"] = jnp.asarray(step)
            m.save(t, step=step, touched={TP: np.array([i])})
        assert [r["kind"] for r in m.generations()] == \
            ["full", "delta", "delta", "delta"]
        got = m.restore()
    with cm.CheckpointManager(db) as m2:
        m2.save(t, step=4)
        want = m2.restore()
    _assert_trees_equal(got, want)


def test_delta_rows_preserve_ml_dtypes_bit_exact(tmp_path):
    """bfloat16 tables: npz stores journaled rows as uint16 bit-pattern
    views (ckpt_io._npz_safe), so restore must reinterpret bits via the
    manifest's ``rows_dtype`` — a value cast would turn every journaled
    row into garbage numerics while the file still crc-verifies."""
    d = str(tmp_path / "c")
    t = {"params": {"emb": {"sharded_embeddings":
                            jnp.zeros((8, 4), jnp.bfloat16)}},
         "step": jnp.asarray(0)}
    with cm.CheckpointManager(d) as m:
        m.save(t, step=1)
        tbl = t["params"]["emb"]["sharded_embeddings"]
        t["params"]["emb"]["sharded_embeddings"] = \
            tbl.at[jnp.asarray([1, 3])].set(
                jnp.asarray([[0.1] * 4, [-2.5] * 4], jnp.bfloat16))
        m.save(t, step=2, touched={TP: np.array([1, 3])})
        rec = m.generations()[-1]
        assert rec["kind"] == "delta"
        assert rec["rows_dtype"] == {TP: "bfloat16"}
        assert m.verify() == []
        got = m.restore()
    got_tbl = np.asarray(got["params"]["emb"]["sharded_embeddings"])
    want_tbl = np.asarray(t["params"]["emb"]["sharded_embeddings"])
    assert got_tbl.dtype == want_tbl.dtype
    np.testing.assert_array_equal(got_tbl.view(np.uint16),
                                  want_tbl.view(np.uint16))


def test_latest_wins_supersedes_pending_and_keeps_newest(tmp_path):
    """Two saves queued behind a stalled writer: the second supersedes
    the first, and the merged journal restores the NEWEST state —
    including rows only the superseded window touched."""
    d = str(tmp_path / "c")
    t = _tree()
    with cm.CheckpointManager(d, inflight="latest-wins") as m:
        m.save(t, step=1)  # the base full
        faults_lib.get_registry().enable("checkpoint.slow_write",
                                         times=1, delay=0.4)
        t["params"]["emb"]["sharded_embeddings"] = \
            t["params"]["emb"]["sharded_embeddings"].at[2].set(2.0)
        assert m.save_async(t, step=2, touched={TP: np.array([2])})
        # writer stalled on step 2; this one waits in pending...
        t["params"]["emb"]["sharded_embeddings"] = \
            t["params"]["emb"]["sharded_embeddings"].at[5].set(5.0)
        assert m.save_async(t, step=3, touched={TP: np.array([5])})
        # ...and is superseded before the writer ever sees it
        t["params"]["emb"]["sharded_embeddings"] = \
            t["params"]["emb"]["sharded_embeddings"].at[5].set(9.0)
        assert m.save_async(t, step=4, touched={TP: np.array([5])})
        m.flush()
        steps = [r["step"] for r in m.generations()]
        # exactly one of the queued saves was superseded (which one
        # depends on when the writer dequeued), and the newest survived
        assert steps[0] == 1 and steps[-1] == 4
        assert len(steps) == 3, steps
        assert m.verify() == []
        got = m.restore()
        tbl = np.asarray(got["params"]["emb"]["sharded_embeddings"])
        assert tbl[5, 0] == 9.0 and tbl[2, 0] == 2.0
    snap = metrics_lib.get_registry().snapshot()
    assert snap.get("ckpt.skipped", 0) >= 1


def test_skip_policy_drops_while_in_flight(tmp_path):
    d = str(tmp_path / "c")
    t = _tree()
    with cm.CheckpointManager(d, inflight="skip") as m:
        faults_lib.get_registry().enable("checkpoint.slow_write",
                                         times=1, delay=0.4)
        assert m.save_async(t, step=1)
        assert m.save_async(t, step=2) is False  # writer busy: dropped
        m.flush()
        assert [r["step"] for r in m.generations()] == [1]
    assert metrics_lib.get_registry().snapshot().get("ckpt.skipped",
                                                     0) >= 1


def test_save_for_exit_reuses_inflight_snapshot(tmp_path):
    """The SIGTERM path: with a write already in flight, the exit save
    drains it and reports ITS step instead of paying a fresh device
    sync inside the grace window."""
    d = str(tmp_path / "c")
    t = _tree()
    with cm.CheckpointManager(d) as m:
        faults_lib.get_registry().enable("checkpoint.slow_write",
                                         times=1, delay=0.3)
        assert m.save_async(t, step=7)
        assert m.save_for_exit(t, step=9, timeout=30.0) == 7
        assert [r["step"] for r in m.generations()] == [7]
        # nothing in flight: a fresh blocking save reports its own step
        assert m.save_for_exit(t, step=9, timeout=30.0) == 9


def test_retention_gc_never_breaks_a_live_chain(tmp_path):
    """keep_last=1 with a delta chain: the base full must survive GC as
    long as a visible delta depends on it, and the swept generations are
    recorded in a ``gc`` manifest line before their bytes vanish."""
    d = str(tmp_path / "c")
    t = _tree()
    with cm.CheckpointManager(d, keep_last=1, compact_every=100) as m:
        m.save(t, step=1)
        for step in range(2, 6):
            t["params"]["emb"]["sharded_embeddings"] = \
                t["params"]["emb"]["sharded_embeddings"].at[step].set(
                    float(step))
            m.save(t, step=step, touched={TP: np.array([step])})
        assert m.verify() == []
        _assert_trees_equal(m.restore(), t)
        # now break the chain dependency: two fresh FULLS — the old
        # base + deltas become collectable, and only then are swept
        m.save(t, step=6, force_full=True)
        m.save(t, step=7, force_full=True)
        recs, gcd = cm.read_manifest(d)
        assert gcd, "GC never fired"
        on_disk = {n for n in os.listdir(d) if n != cm.MANIFEST}
        assert not any(r["dir"] in on_disk for r in recs
                       if r.get("kind") != "gc" and r["gen"] in gcd)
        assert m.verify() == []
        _assert_trees_equal(m.restore(), t)


def test_anchor_generations_survive_retention(tmp_path):
    d = str(tmp_path / "c")
    t = _tree()
    with cm.CheckpointManager(d, keep_last=2, anchor_every=3,
                              delta=False) as m:
        for step in range(8):
            t["step"] = jnp.asarray(step)
            m.save(t, step=step)
        steps = [r["step"] for r in m.generations()]
    # ordinals 0, 3, 6 are anchors; 6 and 7 are the last-2
    assert steps == [0, 3, 6, 7], steps


def test_torn_manifest_tail_is_ignored(tmp_path):
    d = str(tmp_path / "c")
    t = _tree()
    with cm.CheckpointManager(d) as m:
        m.save(t, step=1)
    # a kill -9 mid-append leaves a torn final line: reader skips it
    with open(os.path.join(d, cm.MANIFEST), "a") as f:
        f.write('{"kind": "full", "gen": "999999-dead", "ste')
    assert [r["step"] for r in cm.visible_generations(d)] == [1]
    tree, rec = cm.restore_path(d)
    assert rec["step"] == 1
    _assert_trees_equal(tree, t)


def test_corrupt_generation_falls_back_to_older(tmp_path):
    d = str(tmp_path / "c")
    t = _tree(w_val=1.0)
    with cm.CheckpointManager(d, delta=False) as m:
        m.save(t, step=1)
        t2 = _tree(w_val=2.0)
        m.save(t2, step=2)
        newest = m.generations()[-1]
    gen_dir = os.path.join(d, newest["dir"])
    victim = next(os.path.join(gen_dir, f) for f in os.listdir(gen_dir)
                  if f.endswith(".npz"))
    with open(victim, "r+b") as f:
        f.seek(0)
        f.write(b"\xde\xad\xbe\xef")
    errors, _ = cm.verify_path(d)
    assert errors, "corruption not detected"
    tree, rec = cm.restore_path(d)  # falls back to the clean gen
    assert rec["step"] == 1
    _assert_trees_equal(tree, t)


def test_write_failure_rewinds_chain_and_forces_full(tmp_path):
    d = str(tmp_path / "c")
    t = _tree()
    with cm.CheckpointManager(d, retries=1, retry_delay=0.01) as m:
        m.save(t, step=1)
        faults_lib.get_registry().enable("checkpoint.write_fail",
                                         times=1)
        t["params"]["emb"]["sharded_embeddings"] = \
            t["params"]["emb"]["sharded_embeddings"].at[1].set(1.0)
        with pytest.raises(OSError):
            m.save(t, step=2, touched={TP: np.array([1])})
        # failed write: the NEXT save must not chain onto the ghost
        t["params"]["emb"]["sharded_embeddings"] = \
            t["params"]["emb"]["sharded_embeddings"].at[2].set(2.0)
        m.save(t, step=3, touched={TP: np.array([2])})
        recs = m.generations()
        assert recs[-1]["kind"] == "full"  # forced: no dangling prev
        assert m.verify() == []
        _assert_trees_equal(m.restore(), t)
    snap = metrics_lib.get_registry().snapshot()
    assert snap.get("ckpt.write_errors", 0) >= 1


def test_compact_folds_deltas_into_fresh_full(tmp_path):
    d = str(tmp_path / "c")
    t = _tree()
    with cm.CheckpointManager(d, compact_every=100) as m:
        m.save(t, step=1)
        for step in (2, 3):
            t["params"]["emb"]["sharded_embeddings"] = \
                t["params"]["emb"]["sharded_embeddings"].at[step].set(
                    float(step))
            m.save(t, step=step, touched={TP: np.array([step])})
        assert m.generations()[-1]["kind"] == "delta"
        gen = m.compact()
        newest = m.generations()[-1]
        assert newest["kind"] == "full" and newest["gen"] == gen
        _assert_trees_equal(m.restore(), t)


def test_delta_cadence_promotes_full_every_compact_every(tmp_path):
    d = str(tmp_path / "c")
    t = _tree()
    with cm.CheckpointManager(d, compact_every=2, keep_last=100) as m:
        for step in range(6):
            t["step"] = jnp.asarray(step)
            m.save(t, step=step, touched={TP: np.array([0])})
        kinds = [r["kind"] for r in m.generations()]
    assert kinds == ["full", "delta", "delta", "full", "delta",
                     "delta"], kinds


# -- CLI ----------------------------------------------------------------------

def test_cli_ls_verify_compact(tmp_path, capsys):
    d = str(tmp_path / "c")
    t = _tree()
    with cm.CheckpointManager(d, compact_every=100) as m:
        m.save(t, step=1)
        m.save(t, step=2, touched={TP: np.array([0])})
    assert cm.main(["ls", d]) == 0
    out = capsys.readouterr().out
    assert "full" in out and "delta" in out
    assert cm.main(["verify", d]) == 0
    assert cm.main(["compact", d]) == 0
    assert cm.main(["verify", d]) == 0
    # corrupt the newest generation: verify must exit non-zero
    newest = cm.visible_generations(d)[-1]
    gen_dir = os.path.join(d, newest["dir"])
    victim = next(os.path.join(gen_dir, f) for f in os.listdir(gen_dir)
                  if f.endswith(".npz"))
    with open(victim, "r+b") as f:
        f.write(b"\xde\xad\xbe\xef")
    capsys.readouterr()
    assert cm.main(["verify", d]) == 1
    assert "ERROR" in capsys.readouterr().out


# -- estimator integration ----------------------------------------------------

def _ncf():
    from analytics_zoo_tpu.models import NeuralCF
    return NeuralCF(user_count=64, item_count=40, class_num=2,
                    user_embed=8, item_embed=8, hidden_layers=(16, 8),
                    mf_embed=8, sharded_embeddings=True)


def _ratings(n=256, seed=42):
    rng = np.random.default_rng(seed)
    x = np.stack([rng.integers(0, 64, n),
                  rng.integers(0, 40, n)], 1).astype(np.int32)
    y = (rng.random(n) < 0.5).astype(np.int32)
    return x, y


def test_estimator_async_equals_sync_bit_identical(tmp_path):
    """The restore-equivalence acceptance: the same fit checkpointed
    through the async manager and through the sync ckpt_io path must
    load back bit-identical — params, opt state, embedding rows."""
    from analytics_zoo_tpu.orca.learn import Estimator
    from analytics_zoo_tpu.orca.learn.trigger import SeveralIteration
    init_orca_context("local")
    x, y = _ratings()
    da, ds = str(tmp_path / "async"), str(tmp_path / "sync")
    kw = dict(loss="sparse_categorical_crossentropy", optimizer="adam",
              learning_rate=1e-2, seed=7)
    ea = Estimator.from_keras(_ncf(), model_dir=da,
                              checkpoint_async=True,
                              checkpoint_inflight="block", **kw)
    ea.fit((x, y), epochs=2, batch_size=64, verbose=False,
           checkpoint_trigger=SeveralIteration(2))
    es = Estimator.from_keras(_ncf(), model_dir=ds, **kw)
    es.fit((x, y), epochs=2, batch_size=64, verbose=False,
           checkpoint_trigger=SeveralIteration(2))
    ra = Estimator.from_keras(_ncf(), model_dir=da,
                              checkpoint_async=True, **kw)
    ra.load(da)
    rs = Estimator.from_keras(_ncf(), model_dir=ds, **kw)
    rs.load(ds)
    keys = ("params", "state", "opt_state")
    _assert_trees_equal(jax.device_get({k: ra._ts[k] for k in keys}),
                        jax.device_get({k: rs._ts[k] for k in keys}))
    assert int(np.asarray(ra._ts["step"])) == \
        int(np.asarray(rs._ts["step"]))
    assert ra._ckpt_mgr.verify() == []
    kinds = [r["kind"] for r in ra._ckpt_mgr.generations()]
    assert kinds[0] == "full" and "delta" in kinds, kinds


def test_estimator_async_restores_error_feedback_exactly(tmp_path):
    """int8 grad compression (dense model — sparse forbids it): the
    ``ts["ef"]`` residuals ride the async checkpoint bit-exactly."""
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.orca.learn import Estimator
    init_orca_context("local")
    rng = np.random.default_rng(0)
    x = rng.normal(size=(128, 4)).astype(np.float32)
    y = rng.normal(size=(128, 1)).astype(np.float32)
    d = str(tmp_path / "m")
    kw = dict(loss="mse", learning_rate=1e-3, seed=3,
              grad_compression="int8")
    est = Estimator.from_keras(
        nn.Sequential([nn.Dense(8, activation="relu"), nn.Dense(1)]),
        model_dir=d, checkpoint_async=True, **kw)
    est.fit((x, y), epochs=1, batch_size=32, verbose=False,
            checkpoint_trigger="every_epoch")
    est._ckpt_mgr.flush()
    est2 = Estimator.from_keras(
        nn.Sequential([nn.Dense(8, activation="relu"), nn.Dense(1)]),
        model_dir=d, checkpoint_async=True, **kw)
    est2.load(d)
    keys = ("params", "opt_state", "ef")
    _assert_trees_equal(jax.device_get({k: est._ts[k] for k in keys}),
                        jax.device_get({k: est2._ts[k] for k in keys}))


def test_checkpoint_async_resumes_legacy_sync_checkpoint(tmp_path):
    """checkpoint_async=True turned on over a model_dir holding a
    pre-manager sync checkpoint (ckpt_io layout, no MANIFEST.jsonl)
    must resume from it — not crash on a missing manifest — and the
    next trigger save starts the manifest with a full generation."""
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.orca.learn import Estimator

    def _model():
        return nn.Sequential([nn.Dense(8, activation="relu"),
                              nn.Dense(1)])

    init_orca_context("local")
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 4)).astype(np.float32)
    y = rng.normal(size=(64, 1)).astype(np.float32)
    d = str(tmp_path / "m")
    kw = dict(loss="mse", learning_rate=1e-3, seed=3)
    legacy = Estimator.from_keras(_model(), model_dir=d, **kw)
    legacy.fit((x, y), epochs=1, batch_size=32, verbose=False)
    legacy.save(d)
    assert ckpt_io.exists(d) and not cm.has_manifest(d)
    est = Estimator.from_keras(_model(), model_dir=d,
                               checkpoint_async=True, **kw)
    est.load(d)  # routes to the legacy layout, not the empty manifest
    _assert_trees_equal(jax.device_get(est._ts["params"]),
                        jax.device_get(legacy._ts["params"]))
    assert int(np.asarray(est._ts["step"])) == \
        int(np.asarray(legacy._ts["step"]))
    # auto_resume + trigger saves upgrade the dir to manifest format
    est2 = Estimator.from_keras(_model(), model_dir=d,
                                checkpoint_async=True, **kw)
    est2.fit((x, y), epochs=2, batch_size=32, verbose=False,
             checkpoint_trigger="every_epoch", auto_resume=True)
    est2._ckpt_mgr.flush()
    gens = est2._ckpt_mgr.generations()
    assert gens and gens[0]["kind"] == "full"
    assert est2._ckpt_mgr.verify() == []


def test_checkpoint_async_requires_model_dir():
    from analytics_zoo_tpu.orca.learn import Estimator
    import analytics_zoo_tpu.nn as nn
    init_orca_context("local")
    with pytest.raises(ValueError, match="model_dir"):
        Estimator.from_keras(nn.Dense(1), loss="mse",
                             checkpoint_async=True)


def test_bad_inflight_policy_rejected(tmp_path):
    with pytest.raises(ValueError, match="inflight"):
        cm.CheckpointManager(str(tmp_path / "c"), inflight="yolo")


# -- bench harness knows the checkpoint config --------------------------------

def test_bench_has_checkpoint_config():
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        import bench
    finally:
        sys.path.pop(0)
    assert "checkpoint" in bench.CONFIGS
    assert callable(bench._BENCHES["checkpoint"])
    assert "checkpoint" in bench._BUDGET


# -- serving integration ------------------------------------------------------

def test_swap_from_checkpoint_serves_latest_generation(tmp_path):
    from analytics_zoo_tpu.serving import ModelRegistry
    d = str(tmp_path / "c")
    with cm.CheckpointManager(d, delta=False) as m:
        m.save(_tree(w_val=1.0), step=1)
        m.save(_tree(w_val=5.0), step=2)

    class _M:
        def __init__(self, w):
            self.w = w

        def predict(self, xs):
            return np.asarray(xs, np.float32) * self.w

    reg = ModelRegistry()
    reg.register("default", _M(0.0), version="v1")
    seen = {}

    def loader(tree, rec):
        seen.update(rec)
        return _M(float(np.asarray(tree["params"]["w"])[0, 0]))

    ver = reg.swap_from_checkpoint("default", loader, d)
    assert ver == f"ckpt-{seen['gen']}"
    assert seen["step"] == 2
    model, _, active = reg.resolve("default")
    assert active == ver
    np.testing.assert_allclose(model.predict(np.ones(2, np.float32)),
                               [5.0, 5.0])
    # an unchanged checkpoint refresh collides loudly, not silently
    with pytest.raises(ValueError, match="already has a version"):
        reg.swap_from_checkpoint("default", loader, d)
