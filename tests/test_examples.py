"""Examples-as-tests (reference pattern: SURVEY.md §4.5 — the reference ran
pyzoo/zoo/examples/* at toy scale in its integration CI so the documented
entry points could never rot).  Each example is executed as a real
subprocess — the same way a user would run it — at the smallest scale that
still exercises the full path."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(REPO, "examples")


def _run(script: str, *args: str) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, script), *args],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)


def test_lenet_example():
    proc = _run("lenet_mnist.py", "--epochs", "1", "--samples", "128",
                "--batch-size", "32")
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "validation:" in proc.stdout


def test_bert_finetune_example():
    proc = _run("bert_finetune.py", "--epochs", "1", "--samples", "64",
                "--batch-size", "16", "--seq-len", "32", "--hidden", "64",
                "--layers", "1")
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "validation:" in proc.stdout


def test_ncf_friesian_example():
    pytest.importorskip("pandas")
    proc = _run("ncf_friesian.py", "--epochs", "1", "--batch-size", "128")
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "test:" in proc.stdout and "top-3" in proc.stdout


def test_resnet_imageset_example():
    pytest.importorskip("PIL")
    proc = _run("resnet_imageset.py", "--epochs", "1", "--batch-size", "16",
                "--image-size", "32", "--num-workers", "2")
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "train-set eval:" in proc.stdout


def test_cluster_serving_example():
    proc = _run("cluster_serving.py")
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "TCP client prediction:" in proc.stdout
    assert "HTTP client prediction:" in proc.stdout
    assert "service stats:" in proc.stdout


def test_chronos_autots_example():
    pytest.importorskip("pandas")
    proc = _run("chronos_autots.py", "--epochs", "1", "--n-sampling", "1")
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "test metrics:" in proc.stdout
    assert "reloaded prediction shape:" in proc.stdout


def test_torch_import_example():
    proc = _run("torch_import.py", "--epochs", "1", "--samples", "96",
                "--batch-size", "32")
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "validation:" in proc.stdout
    assert "max |diff|" in proc.stdout


def test_int8_aot_serving_example():
    proc = _run("int8_aot_serving.py")
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "top-1 agreement" in proc.stdout
    assert "outputs identical" in proc.stdout
