"""Docs are executable: every bare ```python block in docs/*.md runs
(VERDICT r2 #10 — per-subsystem pages with runnable snippets,
import-checked in CI).  Blocks within one file share a namespace and run
in order; illustrative snippets that need external files/servers are
fenced as ```python no-run and excluded."""

import pathlib
import re

import pytest

DOCS = sorted((pathlib.Path(__file__).parent.parent / "docs").glob("*.md"))
_BLOCK = re.compile(r"```python\n(.*?)```", re.S)


@pytest.mark.parametrize("doc", DOCS, ids=[d.name for d in DOCS])
def test_doc_snippets_execute(doc):
    blocks = _BLOCK.findall(doc.read_text())
    if not blocks:
        pytest.skip("no python blocks")
    ns: dict = {}
    for i, code in enumerate(blocks):
        try:
            exec(compile(code, f"{doc.name}[block {i}]", "exec"), ns)
        except Exception as e:
            pytest.fail(f"{doc.name} block {i} failed: {e}")
