"""TextSet pipeline (VERDICT r1 missing #5): tokenize → word2idx → pad →
feed, wired into TextClassifier training.
"""

import numpy as np
import pytest

from analytics_zoo_tpu.core import init_orca_context
from analytics_zoo_tpu.data import TextSet

TEXTS = [
    "The cat sat on the mat",
    "Dogs chase the cat around",
    "I love training models on TPUs",
    "XLA compiles the whole step",
    "the mat was sat on by a cat",
    "models love big batches",
    "a dog and a cat met",
    "compilers fuse elementwise ops",
]
LABELS = [0, 0, 1, 1, 0, 1, 0, 1]


def test_tokenize_normalize_word2idx():
    ts = TextSet.from_texts(TEXTS, LABELS).tokenize().normalize().word2idx()
    assert ts.word_index is not None
    # most frequent word is "the" → id 2 (0=pad, 1=oov)
    assert ts.word_index["the"] == 2
    assert "cat" in ts.word_index
    # ids are consistent with the index
    ts.shape_sequence(8)
    x, y = ts.to_numpy()
    assert x.shape == (8, 8) and x.dtype == np.int32
    assert y.shape == (8,)
    row = x[0]
    toks = [w.lower() for w in TEXTS[0].split()]
    for tok, idx in zip(toks, row):
        assert ts.word_index[tok] == idx


def test_shape_sequence_pad_and_truncate():
    ts = TextSet.from_texts(["a b c d e f", "a b"]).word2idx()
    ts.shape_sequence(4, trunc_mode="pre")
    x, _ = ts.to_numpy()
    assert x.shape == (2, 4)
    assert np.all(x[1][2:] == 0)           # padded with PAD_ID
    ts2 = TextSet.from_texts(["a b c d e f"]).word2idx()
    pre = ts2.shape_sequence(3, trunc_mode="pre").to_numpy()[0][0]
    ts3 = TextSet.from_texts(["a b c d e f"]).word2idx()
    post = ts3.shape_sequence(3, trunc_mode="post").to_numpy()[0][0]
    assert not np.array_equal(pre, post)   # tail kept vs head kept


def test_word2idx_existing_index_and_oov():
    train = TextSet.from_texts(TEXTS[:4]).word2idx()
    val = TextSet.from_texts(["the zebra sat"]).word2idx(
        existing_index=train.word_index)
    val.shape_sequence(4)
    x, _ = val.to_numpy()
    assert x[0][0] == train.word_index["the"]
    assert x[0][1] == 1                    # "zebra" unseen → OOV id
    assert val.vocab_size() == train.vocab_size()


def test_word_index_round_trip(tmp_path):
    ts = TextSet.from_texts(TEXTS).word2idx(max_words_num=10)
    p = str(tmp_path / "wi.json")
    ts.save_word_index(p)
    wi = TextSet.load_word_index(p)
    assert wi == ts.word_index


def test_textset_min_freq():
    ts = TextSet.from_texts(TEXTS).word2idx(min_freq=2)
    assert "the" in ts.word_index
    assert "compiles" not in ts.word_index  # appears once


def test_textset_feeds_textclassifier():
    """The reference flow: TextSet pipeline → TextClassifier.fit."""
    from analytics_zoo_tpu.models import TextClassifier
    from analytics_zoo_tpu.orca.learn import Estimator
    init_orca_context("local")
    ts = (TextSet.from_texts(TEXTS, LABELS).tokenize().normalize()
          .word2idx().shape_sequence(8))
    model = TextClassifier(class_num=2, vocab_size=ts.vocab_size(),
                           token_length=16, sequence_length=8,
                           encoder="cnn", encoder_output_dim=16)
    est = Estimator.from_keras(model,
                               loss="sparse_categorical_crossentropy",
                               learning_rate=1e-2)
    hist = est.fit(ts.to_feed(batch_size=8), epochs=2, batch_size=8,
                   verbose=False)
    assert np.isfinite(hist["loss"][-1])
    x, _ = ts.to_numpy()
    preds = est.predict(x, batch_size=8)
    assert preds.shape == (8, 2)
