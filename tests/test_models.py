"""Model zoo tests (reference test pattern, SURVEY.md §4: build the model,
train/predict on tiny synthetic data, save/load round-trip)."""

import jax
import numpy as np
import pytest

from analytics_zoo_tpu.core import init_orca_context


@pytest.fixture(autouse=True)
def _ctx():
    init_orca_context("local")
    yield


def test_neuralcf_train_and_recommend(rng):
    from analytics_zoo_tpu.models import NeuralCF, UserItemPrediction
    m = NeuralCF(user_count=20, item_count=30, class_num=2,
                 hidden_layers=(16, 8))
    m.compile(loss="sparse_categorical_crossentropy", learning_rate=0.01,
              metrics=["accuracy"])
    x = np.stack([rng.integers(0, 20, 256), rng.integers(0, 30, 256)], 1)
    y = ((x[:, 0] + x[:, 1]) % 2).astype(np.int32)  # learnable parity rule
    hist = m.fit((x.astype(np.int32), y), epochs=5, batch_size=64,
                 verbose=False)
    assert hist["loss"][-1] < hist["loss"][0]
    recs = m.recommend_for_user([1, 2], max_items=3)
    assert len(recs) == 6
    assert all(isinstance(r, UserItemPrediction) for r in recs)
    recs_i = m.recommend_for_item([5], max_users=4)
    assert len(recs_i) == 4 and all(r.item_id == 5 for r in recs_i)


def test_neuralcf_save_load_roundtrip(rng, tmp_path):
    from analytics_zoo_tpu.models import NeuralCF, ZooModel
    m = NeuralCF(user_count=10, item_count=10, hidden_layers=(8,))
    m.compile(loss="sparse_categorical_crossentropy")
    x = np.stack([rng.integers(0, 10, 32), rng.integers(0, 10, 32)], 1
                 ).astype(np.int32)
    y = rng.integers(0, 2, 32).astype(np.int32)
    m.fit((x, y), epochs=1, batch_size=16, verbose=False)
    p1 = m.predict(x)
    path = str(tmp_path / "ncf")
    m.save_model(path)
    m2 = ZooModel.load_model(path)
    m2.compile_with_loaded(loss="sparse_categorical_crossentropy")
    p2 = m2.predict(x)
    np.testing.assert_allclose(p1, p2, atol=1e-6)


def test_wide_and_deep_forward(rng):
    from analytics_zoo_tpu.models import WideAndDeep
    m = WideAndDeep(class_num=2, wide_base_dims=(5, 5), wide_cross_dims=(10,),
                    indicator_dims=(3,), embed_in_dims=(20, 20),
                    embed_out_dims=(4, 4), continuous_cols=2,
                    hidden_layers=(16, 8))
    m.compile(loss="sparse_categorical_crossentropy", learning_rate=0.01)
    n = 64
    wide = (rng.random((n, 20)) < 0.1).astype(np.float32)
    ind = (rng.random((n, 3)) < 0.3).astype(np.float32)
    emb = rng.integers(0, 20, (n, 2)).astype(np.float32)
    cont = rng.normal(size=(n, 2)).astype(np.float32)
    x = np.concatenate([wide, ind, emb, cont], axis=1)
    y = rng.integers(0, 2, n).astype(np.int32)
    hist = m.fit((x, y), epochs=2, batch_size=32, verbose=False)
    assert np.isfinite(hist["loss"][-1])
    for mt in ("wide", "deep"):
        sub = WideAndDeep(class_num=2, model_type=mt, wide_base_dims=(5, 5),
                          wide_cross_dims=(10,), indicator_dims=(3,),
                          embed_in_dims=(20, 20), embed_out_dims=(4, 4),
                          continuous_cols=2, hidden_layers=(8,))
        out, _ = sub.apply(sub.init(jax.random.PRNGKey(0), x[:4]), x[:4])
        assert out.shape == (4, 2)


def test_session_recommender(rng):
    from analytics_zoo_tpu.models import SessionRecommender
    m = SessionRecommender(item_count=50, item_embed=16,
                           rnn_hidden_layers=(16, 8), session_length=6,
                           include_history=True, history_length=4)
    m.compile(loss="sparse_categorical_crossentropy", learning_rate=0.01)
    x = rng.integers(0, 50, (64, 10)).astype(np.int32)
    y = rng.integers(0, 50, 64).astype(np.int32)
    hist = m.fit((x, y), epochs=1, batch_size=32, verbose=False)
    assert np.isfinite(hist["loss"][0])
    recs = m.recommend_for_session(x[:3], max_items=4)
    assert len(recs) == 3 and len(recs[0]) == 4


def test_text_classifier_all_encoders(rng):
    from analytics_zoo_tpu.models import TextClassifier
    x = rng.integers(0, 100, (32, 20)).astype(np.int32)
    y = rng.integers(0, 3, 32).astype(np.int32)
    for enc in ("cnn", "lstm", "gru"):
        m = TextClassifier(class_num=3, vocab_size=100, token_length=16,
                           sequence_length=20, encoder=enc,
                           encoder_output_dim=16)
        m.compile(loss="sparse_categorical_crossentropy", learning_rate=0.01)
        hist = m.fit((x, y), epochs=1, batch_size=16, verbose=False)
        assert np.isfinite(hist["loss"][0]), enc
        assert m.predict_classes(x).shape == (32,)


def test_knrm_ranking(rng):
    from analytics_zoo_tpu.models import KNRM
    m = KNRM(text1_length=5, text2_length=10, vocab_size=50, embed_size=16,
             kernel_num=11)
    m.compile(loss="binary_crossentropy", learning_rate=0.01)
    x = rng.integers(0, 50, (64, 15)).astype(np.int32)
    # matching docs share tokens with query
    y = np.array([1.0 if len(set(r[:5]) & set(r[5:])) else 0.0 for r in x],
                 np.float32)[:, None]
    hist = m.fit((x, y), epochs=3, batch_size=32, verbose=False)
    assert np.isfinite(hist["loss"][-1])


def test_anomaly_detector_pipeline(rng):
    from analytics_zoo_tpu.models import AnomalyDetector, unroll
    t = np.arange(300, dtype=np.float32)
    series = np.sin(t / 10) + 0.05 * rng.normal(size=300)
    series[250] += 5.0  # inject an anomaly
    x, y = unroll(series, unroll_length=10)
    assert x.shape == (290, 10, 1) and y.shape == (290,)
    m = AnomalyDetector(feature_shape=(10, 1), hidden_layers=(8, 8),
                        dropouts=(0.0, 0.0))
    m.compile(loss="mse", learning_rate=0.01)
    m.fit((x, y[:, None]), epochs=3, batch_size=64, verbose=False)
    pred = m.predict(x)
    anomalies = m.detect_anomalies(y, pred, anomaly_fraction=0.01)
    # the injected spike (unrolled index 240 = point 250) must be flagged
    assert any(235 <= a <= 245 for a in anomalies)


def test_seq2seq_fit_and_infer(rng):
    from analytics_zoo_tpu.models import Seq2seq
    m = Seq2seq(vocab_size=20, embed_dim=16, hidden_size=16,
                encoder_length=6, decoder_length=4, use_attention=True)
    m.compile(loss="sparse_categorical_crossentropy", learning_rate=0.01)
    x = rng.integers(0, 20, (64, 10)).astype(np.int32)
    y = rng.integers(0, 20, (64, 4)).astype(np.int32)
    hist = m.fit((x, y), epochs=1, batch_size=32, verbose=False)
    assert np.isfinite(hist["loss"][0])
    decoded = m.infer(x[:3, :6], start_id=0, max_length=4)
    assert decoded.shape == (3, 4)
    assert decoded.dtype in (np.int32, np.int64)


def test_resnet_variants(rng):
    from analytics_zoo_tpu.models import ResNet
    x = rng.normal(size=(2, 32, 32, 3)).astype(np.float32)
    for depth in (18, 50):
        m = ResNet(depth=depth, class_num=10)
        variables = m.init(jax.random.PRNGKey(0), x)
        out, _ = m.apply(variables, x)
        assert out.shape == (2, 10), depth
    # bf16 path keeps f32 head output
    m = ResNet(depth=18, class_num=10, dtype="bfloat16")
    out, _ = m.apply(m.init(jax.random.PRNGKey(0), x), x)
    assert out.dtype == np.float32 or str(out.dtype) == "float32"


def test_image_classifier_topn(rng):
    from analytics_zoo_tpu.models import ImageClassifier
    labels = [f"class_{i}" for i in range(10)]
    m = ImageClassifier(depth=18, class_num=10, labels=labels)
    m.compile(loss="sparse_categorical_crossentropy")
    imgs = rng.normal(size=(4, 32, 32, 3)).astype(np.float32)
    preds = m.predict_image_set(imgs, top_n=3)
    assert len(preds) == 4 and len(preds[0]) == 3
    assert preds[0][0][0].startswith("class_")


def test_ssd_object_detector(rng):
    from analytics_zoo_tpu.models import ObjectDetector
    from analytics_zoo_tpu.models.objectdetection import nms
    m = ObjectDetector(class_num=4, backbone_depth=18, image_size=64)
    m.compile(loss="mse")
    imgs = rng.normal(size=(2, 64, 64, 3)).astype(np.float32)
    raw = m.predict(imgs)
    assert raw.shape[0] == 2 and raw.shape[2] == 4 + 4
    assert raw.shape[1] == len(m.ssd.anchors)
    dets = m.predict_image_set(imgs, score_threshold=0.0)
    assert len(dets) == 2
    # NMS sanity: overlapping boxes collapse
    boxes = np.array([[0, 0, 1, 1], [0, 0, 0.95, 0.95], [2, 2, 3, 3]],
                     np.float32)
    keep = nms(boxes, np.array([0.9, 0.8, 0.7], np.float32), 0.5)
    assert keep == [0, 2]


def test_bert_classifier_and_squad(rng):
    from analytics_zoo_tpu.models import BERTClassifier, BERTSQuAD
    from analytics_zoo_tpu.models.bert import squad_span_loss
    kw = dict(vocab_size=100, hidden_size=32, n_layers=2, n_heads=2,
              max_position=16)
    x = rng.integers(0, 100, (8, 12)).astype(np.int32)
    m = BERTClassifier(class_num=3, **kw)
    m.compile(loss="sparse_categorical_crossentropy", learning_rate=1e-3)
    y = rng.integers(0, 3, 8).astype(np.int32)
    hist = m.fit((x, y), epochs=1, batch_size=8, verbose=False)
    assert np.isfinite(hist["loss"][0])

    sq = BERTSQuAD(**kw)
    sq.compile(loss=squad_span_loss, learning_rate=1e-3)
    spans = np.stack([rng.integers(0, 12, 8), rng.integers(0, 12, 8)], 1
                     ).astype(np.int32)
    hist = sq.fit((x, spans), epochs=1, batch_size=8, verbose=False)
    assert np.isfinite(hist["loss"][0])


def test_load_model_then_plain_compile_keeps_weights(rng, tmp_path):
    """compile() after load_model must start from loaded weights
    (regression: silently re-initialized)."""
    from analytics_zoo_tpu.models import NeuralCF, ZooModel
    m = NeuralCF(user_count=10, item_count=10, hidden_layers=(8,))
    m.compile(loss="sparse_categorical_crossentropy")
    x = np.stack([rng.integers(0, 10, 32), rng.integers(0, 10, 32)], 1
                 ).astype(np.int32)
    y = rng.integers(0, 2, 32).astype(np.int32)
    m.fit((x, y), epochs=1, batch_size=16, verbose=False)
    p1 = m.predict(x)
    path = str(tmp_path / "m")
    m.save_model(path)
    m2 = ZooModel.load_model(path)
    m2.compile(loss="sparse_categorical_crossentropy")  # plain compile
    np.testing.assert_allclose(m2.predict(x), p1, atol=1e-6)


def test_ssd_anchor_count_matches_head_for_odd_sizes(rng):
    """image_size not divisible by 64 must still align anchors with the
    head output (regression: floor-vs-ceil feature map sizes)."""
    from analytics_zoo_tpu.models import SSDLite
    m = SSDLite(class_num=3, backbone_depth=18, image_size=100)
    m.compile(loss="mse")
    imgs = rng.normal(size=(1, 100, 100, 3)).astype(np.float32)
    raw = m.predict(imgs)
    assert raw.shape[1] == len(m.anchors)


def test_recommend_probability_is_positive_class(rng):
    """UserItemPrediction.probability must be P(recommend), not the max
    class prob (regression: confident negatives surfaced as top picks)."""
    from analytics_zoo_tpu.models import NeuralCF
    m = NeuralCF(user_count=8, item_count=8, hidden_layers=(4,))
    m.compile(loss="sparse_categorical_crossentropy")
    x = np.stack([rng.integers(0, 8, 16), rng.integers(0, 8, 16)], 1
                 ).astype(np.int32)
    y = rng.integers(0, 2, 16).astype(np.int32)
    m.fit((x, y), epochs=1, batch_size=16, verbose=False)
    recs = m.recommend_for_user([0], max_items=8)
    pairs = np.stack([np.zeros(8), np.arange(8)], 1).astype(np.int32)
    import jax.nn
    import jax.numpy as jnp
    probs = np.asarray(jax.nn.softmax(jnp.asarray(m.predict(pairs)), -1))
    for r in recs:
        np.testing.assert_allclose(r.probability, 1 - probs[r.item_id, 0],
                                   atol=1e-6)


def test_visualizer_draws_boxes():
    import pytest
    pytest.importorskip("PIL")
    from analytics_zoo_tpu.models import Visualizer
    img = np.zeros((64, 64, 3), np.float32)
    dets = [("cat", 0.9, np.asarray([8.0, 8.0, 30.0, 30.0])),
            ("dog", 0.7, np.asarray([35.0, 35.0, 60.0, 60.0]))]
    out = Visualizer().visualize(img, dets)
    assert out.shape == (64, 64, 3) and out.dtype == np.uint8
    assert out.max() > 0  # something was drawn


def test_tsdataset_to_feed():
    import pandas as pd
    from analytics_zoo_tpu.chronos import TSDataset
    from analytics_zoo_tpu.core import get_mesh
    df = pd.DataFrame({
        "ts": pd.date_range("2026-01-01", periods=80, freq="h"),
        "value": np.arange(80, dtype=np.float32),
    })
    ds = TSDataset.from_pandas(df, dt_col="ts", target_col="value")
    ds.roll(lookback=12, horizon=2)
    feed = ds.to_feed(batch_size=16, shuffle=False)
    batch = next(feed.epoch(get_mesh(), 0))
    assert batch["x"].shape == (16, 12, 1)
    assert batch["y"].shape == (16, 2, 1)


def test_text_classifier_pretrained_embeddings_frozen(tmp_path):
    """TextClassifier with a pretrained embedding table (reference took a
    GloVe file): frozen even under adamw's decoupled weight decay, and
    the frozen semantics survive save_model/load_model."""
    import jax
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.models import TextClassifier, ZooModel
    from analytics_zoo_tpu.orca.learn import Estimator

    rng = np.random.default_rng(0)
    table = rng.normal(size=(50, 16)).astype(np.float32)
    with pytest.raises(ValueError, match="vocab_size"):
        TextClassifier(class_num=2, vocab_size=99, embedding_weights=table)
    m = TextClassifier(class_num=2, vocab_size=50,
                       embedding_weights=table, encoder="cnn",
                       encoder_output_dim=8)
    ids = rng.integers(0, 50, (32, 12)).astype(np.int32)
    y = rng.integers(0, 2, 32).astype(np.int32)
    # adamw: weight decay would shrink a merely-stop_gradient'd table
    est = Estimator.from_keras(m, loss="sparse_categorical_crossentropy",
                               optimizer="adamw", learning_rate=5e-3)
    est.fit((ids, y), epochs=2, batch_size=16, verbose=False)
    trained = np.asarray(
        jax.device_get(est._ts["state"])["embed"]["embeddings"])
    np.testing.assert_array_equal(trained, table)  # frozen, not decayed
    # save/load round-trip keeps the pretrained-frozen architecture
    m.set_estimator(est)
    path = m.save_model(str(tmp_path / "tc"))
    m2 = ZooModel.load_model(path)
    assert m2.embedding_shape == [50, 16]
    m2.compile_with_loaded(loss="sparse_categorical_crossentropy")
    out = m2.predict(ids[:4])
    assert np.asarray(out).shape == (4, 2)
    # the loaded model's frozen table carries the pretrained values
    out_orig = m.predict(ids[:4])
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_orig),
                               atol=1e-5)


def test_nf_resnet_forward_and_identity_at_init(rng):
    """Normalizer-free ResNet (norm='nf'): Scaled WS convs, no BN.
    SkipInit (folded into the last conv's weight scale) makes every
    residual branch exactly zero at init, so each non-transition block
    is the identity."""
    import jax.numpy as jnp

    from analytics_zoo_tpu.models import ResNet
    from analytics_zoo_tpu.models.image import _NFResBlock

    x = rng.normal(size=(2, 32, 32, 3)).astype(np.float32)
    m = ResNet(depth=50, class_num=10, norm="nf")
    variables = m.init(jax.random.PRNGKey(0), x)
    out, _ = m.apply(variables, x, training=True)
    assert out.shape == (2, 10)
    assert bool(jnp.isfinite(out).all())

    # a non-transition nf block is the identity at init
    blk = _NFResBlock(4, stride=1, bottleneck=True, beta=1.0, alpha=0.2)
    h = jnp.asarray(rng.normal(size=(2, 8, 8, 16)).astype(np.float32))
    bv = blk.init(jax.random.PRNGKey(1), h)
    y, _ = blk.apply(bv, h)
    np.testing.assert_allclose(np.asarray(y), np.asarray(h), atol=1e-6)


def test_nf_resnet_depth18_stage0_variance_reset_matches_shortcut(rng):
    """The analytic variance tracker must reset from the SAME
    channel-change-or-stride predicate the block uses for its projected
    shortcut (not ``b == 0``).  Depth-18 stage 0 block 0 is the case the
    two disagreed on: stem channels == f and stride 1, so the block
    takes an IDENTITY shortcut (no proj conv) — the tracker must see it
    as a non-transition too."""
    from analytics_zoo_tpu.models import ResNet
    from analytics_zoo_tpu.models import image as image_mod

    # 1) block side: depth-18 stage0_block0 has no projection, while
    # every striding/widening block does
    x = rng.normal(size=(1, 32, 32, 3)).astype(np.float32)
    m = ResNet(depth=18, class_num=2, norm="nf", width=8)
    variables = m.init(jax.random.PRNGKey(0), x)
    params = variables["params"]
    assert "proj" not in params["stage0_block0"], \
        "depth-18 stage 0 block 0 must keep the identity shortcut"
    assert "proj" in params["stage1_block0"]  # stride-2 transition

    # 2) tracker side: ResNet.forward consults the shared predicate
    # once per NF block, and the depth-18 stage0 decisions are
    # (identity, identity) — the old ``b == 0`` reset said transition
    calls = []
    real = image_mod._nf_transition

    def spy(in_ch, out_ch, stride):
        r = real(in_ch, out_ch, stride)
        calls.append((in_ch, out_ch, stride, r))
        return r

    image_mod._nf_transition = spy
    try:
        m.init(jax.random.PRNGKey(0), x)
    finally:
        image_mod._nf_transition = real
    # depth 18 = [2, 2, 2, 2] basic blocks; forward + block each consult
    # the predicate, so filter to the tracker's view (stage order holds)
    assert calls, "variance tracker no longer consults _nf_transition"
    stage0 = [c for c in calls if c[1] == 8]  # out_channels == width
    assert all(r is False for (_i, _o, _s, r) in stage0), stage0
    strided = [c for c in calls if c[2] == 2]
    assert strided and all(r is True for (*_a, r) in strided)


def test_nf_resnet_skip_gain_learns(rng):
    """The folded SkipInit must still receive gradient at init (the
    weight-space adjoint equals the activation-space sum dy*h), and a
    small NF ResNet must train."""
    from analytics_zoo_tpu.models import ResNet
    from analytics_zoo_tpu.orca.learn import Estimator

    xs = rng.normal(0, 1, (128, 16, 16, 3)).astype(np.float32)
    ys = (rng.integers(0, 2, 128)).astype(np.int32)
    xs[ys == 1, :, :, 0] += 2.0
    m = ResNet(depth=18, class_num=2, norm="nf", width=8)
    est = Estimator.from_keras(m, loss="sparse_categorical_crossentropy",
                               optimizer="adam", learning_rate=3e-3)
    hist = est.fit((xs, ys), epochs=4, batch_size=32, verbose=False)
    assert hist["loss"][-1] < hist["loss"][0] * 0.8, hist["loss"]
    # skip_gain params exist and moved off zero
    leaves = jax.tree_util.tree_leaves_with_path(est._ts["params"])
    gains = [v for p, v in leaves if "skip_gain" in jax.tree_util.keystr(p)]
    assert gains and any(float(abs(g)) > 1e-5 for g in gains)
