"""Cluster-wide telemetry (ISSUE 9): span tracing, gang metric
aggregation, the flight recorder, and the step profiler.

Covers: the span-tree primitives and their ZooConfig knobs, ring
eviction accounting, the registry reset() dangling-series fix,
MetricsRegistry.merge semantics (counters sum / gauge hwm max / bucket
add / replica-label dropping), cross-process gang aggregation edge
cases (empty + torn jsonl, never-beat ranks, restart fold), jsonl
rotation, THE acceptance criteria — a hedged two-replica request whose
``trace.tree`` reconstructs root → attempt spans → server-side
assembly/inference/reply spans, and a hard-killed replica whose flight
record names its in-flight trace ids with zero client-visible failures
— plus the estimator's step profiler (train.mfu, compile events, fit
span tree) and the serving-side instrumentation overhead guard (slow).
"""

import glob
import json
import logging
import os
import socket
import threading
import time
import urllib.request

import numpy as np
import pytest

import analytics_zoo_tpu.nn as nn
from analytics_zoo_tpu.core import flightrec, init_orca_context
from analytics_zoo_tpu.core import metrics as metrics_lib
from analytics_zoo_tpu.core import trace as trace_lib
from analytics_zoo_tpu.core.config import ZooConfig
from analytics_zoo_tpu.core.faults import FaultRegistry
from analytics_zoo_tpu.core.launcher import (_GangStatus,
                                             _fold_gang_snapshots,
                                             aggregate_worker_metrics)
from analytics_zoo_tpu.core.metrics import MetricsRegistry
from analytics_zoo_tpu.serving import (ClusterServing, HTTPFrontend,
                                       InputQueue, OutputQueue,
                                       ReplicaSet)


class _Model:
    """Doubles its input; counts rows; optional fixed delay."""

    def __init__(self, delay: float = 0.0):
        self.delay = delay
        self.calls = []
        self._lock = threading.Lock()

    def predict(self, x):
        if self.delay:
            time.sleep(self.delay)
        with self._lock:
            self.calls.append(np.asarray(x).shape[0])
        return np.asarray(x) * 2.0


def _two_ports():
    ports = []
    for _ in range(2):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        s.close()
    ports.sort(key=lambda p: f"127.0.0.1:{p}")
    return ports


@pytest.fixture
def _restore_trace_config():
    yield
    trace_lib.configure(slow_ms=trace_lib.DEFAULT_SLOW_MS,
                        max_records=trace_lib.DEFAULT_MAX_RECORDS)


@pytest.fixture
def _flight_dir(tmp_path):
    d = str(tmp_path / "flight")
    flightrec.configure(d)
    yield d
    flightrec.configure(None)


# -- span-tree primitives -----------------------------------------------------

def test_span_context_manager_builds_a_tree():
    with trace_lib.span("a.root") as root:
        with root.child("a.mid") as mid:
            with mid.child("a.leaf", work_ms=1.5):
                pass
    roots = trace_lib.tree(root.trace_id)
    assert len(roots) == 1 and roots[0].name == "a.root"
    assert roots[0].record.dur_ms is not None
    (mid_node,) = roots[0].children
    assert mid_node.name == "a.mid"
    (leaf,) = mid_node.children
    assert leaf.name == "a.leaf" and leaf.record.stages["work_ms"] == 1.5
    # find() walks descendants by name
    assert roots[0].find("a.leaf") == [leaf]


def test_orphan_parent_degrades_to_forest_not_error():
    tid = trace_lib.new_trace_id()
    trace_lib.record(tid, "a.child", {}, parent="deadbeef")  # evicted parent
    roots = trace_lib.tree(tid)
    assert [r.name for r in roots] == ["a.child"]


def test_trace_knobs_configurable_via_zooconfig(_restore_trace_config):
    init_orca_context("local", config=ZooConfig(trace_slow_ms=5.0,
                                                trace_ring=16))
    assert trace_lib.SLOW_MS == 5.0
    assert trace_lib.MAX_RECORDS == 16
    tid = trace_lib.new_trace_id()
    for _ in range(40):
        trace_lib.record(tid, "t.x", {})
    assert len(trace_lib.find(tid)) == 16  # ring resized
    snap = metrics_lib.get_registry().snapshot()
    assert snap["trace.spans_dropped"] == 24  # evictions counted


def test_disabled_tracing_records_nothing():
    trace_lib.enabled = False
    try:
        tid = trace_lib.new_trace_id()
        assert trace_lib.record(tid, "t.x", {}) is None
        with trace_lib.span("t.y", trace_id=tid):
            pass
        assert trace_lib.find(tid) == []
    finally:
        trace_lib.enabled = True


def test_slow_warning_folds_server_stage_breakdown(caplog,
                                                   _restore_trace_config):
    """Satellite: the slow-request WARNING carries the per-stage
    breakdown — server-side stage spans in the ring are folded in even
    when the caller only measured a total."""
    tid = trace_lib.new_trace_id()
    trace_lib.record(tid, "server.batch",
                     {"server.queue_wait_ms": 40.0,
                      "server.inference_ms": 1500.0})
    with caplog.at_level(logging.WARNING, logger="analytics_zoo_tpu"):
        trace_lib.maybe_log_slow(tid, "req-1", 1600.0,
                                 {"client.total_ms": 1600.0})
    (line,) = [r.message for r in caplog.records
               if "slow request" in r.message]
    assert "client.total_ms=1600.0ms" in line
    assert "server.inference_ms=1500.0ms" in line
    assert "server.queue_wait_ms=40.0ms" in line


# -- registry reset: the dangling label-series fix ----------------------------

def test_reset_registry_exposition_equals_fresh_for_identical_traffic():
    """Satellite regression: series minted by ONE-SHOT writes before a
    reset used to linger as zero-valued label series no fresh registry
    would have — reset() now retires them (handle-held series still
    survive, zeroed)."""
    def traffic(reg, route):
        reg.counter("t.pinned").inc(2)          # handle API: pinned
        reg.inc("t.req", route=route)           # one-shot: ephemeral
        reg.observe("t.lat_ms", 3.0, route=route)

    used = MetricsRegistry()
    traffic(used, "/old")      # pre-reset traffic mints {route=/old}
    used.reset()
    traffic(used, "/new")
    fresh = MetricsRegistry()
    traffic(fresh, "/new")
    assert used.prometheus() == fresh.prometheus()
    # and the handle contract still holds: pinned series survive reset
    c = used.counter("t.survivor")
    c.inc(5)
    used.reset()
    assert c.value == 0
    c.inc()
    assert used.snapshot()["t.survivor"] == 1


# -- MetricsRegistry.merge ----------------------------------------------------

def test_merge_sums_counters_maxes_gauges_adds_buckets():
    a, b = MetricsRegistry(), MetricsRegistry()
    for reg, n, depth, hwm in ((a, 3, 2, 9), (b, 4, 5, 4)):
        reg.counter("m.req").inc(n)
        g = reg.gauge("m.depth")
        g.set(hwm)
        g.set(depth)
        h = reg.histogram("m.lat", buckets=(1.0, 10.0))
        h.observe(0.5)
        h.observe(5.0)
    merged = MetricsRegistry.merge([a.snapshot(), b.snapshot()])
    assert merged["m.req"] == 7
    assert merged["m.depth"]["value"] == 7    # cluster load = sum
    assert merged["m.depth"]["max"] == 9      # hwm = max
    h = merged["m.lat"]
    assert h["count"] == 4 and h["bucket_counts"] == [2, 2, 0]
    assert h["mean"] == pytest.approx(2.75)
    # summaries recomputed from the MERGED buckets
    assert 0.0 < h["p50"] <= 10.0


def test_merge_drops_replica_labels_into_one_series():
    reg = MetricsRegistry()
    reg.counter("client.retries", replica="h:1").inc(2)
    reg.counter("client.retries", replica="h:2").inc(3)
    reg.counter("router.requests", replica="h:1").inc(1)
    merged = MetricsRegistry.merge([reg.snapshot()],
                                   drop_labels=("replica",))
    assert merged == {"client.retries": 5, "router.requests": 1}


def test_merge_bucket_edge_mismatch_drops_buckets_keeps_totals():
    a, b = MetricsRegistry(), MetricsRegistry()
    a.histogram("m.h", buckets=(1.0, 2.0)).observe(0.5)
    b.histogram("m.h", buckets=(5.0, 9.0)).observe(6.0)
    merged = MetricsRegistry.merge([a.snapshot(), b.snapshot()])
    assert merged["m.h"]["count"] == 2
    assert "bucket_counts" not in merged["m.h"]  # never lie about p50


def test_from_snapshot_round_trips_to_prometheus():
    reg = MetricsRegistry()
    reg.counter("m.c", route="/x").inc(2)
    reg.gauge("m.g").set(3)
    reg.histogram("m.h", buckets=(1.0,)).observe(0.5)
    rebuilt = MetricsRegistry.from_snapshot(reg.snapshot())
    assert rebuilt.prometheus() == reg.prometheus()


# -- gang aggregation ---------------------------------------------------------

def test_gang_fold_counters_sum_across_worker_restart():
    """Satellite: a restarted rank's registry resets to zero — folding
    the latest snapshot per (rank, attempt) and SUMMING counters keeps
    the rank's lifetime total (max-merging would freeze at the larger
    attempt; latest-only would lose pre-restart history)."""
    by = {
        (0, 0): {"train.steps": 10,
                 "q.depth": {"value": 3.0, "max": 7.0}},
        (0, 1): {"train.steps": 4,
                 "q.depth": {"value": 2.0, "max": 5.0}},
        (1, 0): {"train.steps": 9,
                 "q.depth": {"value": 1.0, "max": 2.0}},
    }
    merged = _fold_gang_snapshots(by)
    assert merged["train.steps"] == 23
    # gauge VALUE only from each rank's latest attempt (a dead
    # attempt's queue depth is not load); hwm is max over everything
    assert merged["q.depth"]["value"] == 3.0
    assert merged["q.depth"]["max"] == 7.0


def test_aggregate_worker_metrics_tolerates_empty_torn_and_silent(
        tmp_path):
    d = str(tmp_path)
    with open(os.path.join(d, "metrics_w0.jsonl"), "w") as f:
        f.write(json.dumps({"rank": 0, "attempt": 0, "step": 3,
                            "metrics": {"c": 1}}) + "\n")
        f.write(json.dumps({"rank": 0, "attempt": 0, "step": 9,
                            "metrics": {"c": 5}}) + "\n")
        f.write('{"torn half-line')         # worker died mid-write
    open(os.path.join(d, "metrics_w1.jsonl"), "w").close()  # never beat
    with open(os.path.join(d, "metrics_w2.jsonl"), "w") as f:
        # beats but never carried a registry snapshot (legacy payload)
        f.write(json.dumps({"rank": 2, "attempt": 0, "step": 1}) + "\n")
    assert aggregate_worker_metrics(d) == {"c": 5}  # latest per rank
    # a size rotation mid-attempt: the CURRENT file's newer snapshot
    # must win over the rotated .1 generation (plain name sorting would
    # process .jsonl before .jsonl.1 and fold the stale value)
    with open(os.path.join(d, "metrics_w0.jsonl.1"), "w") as f:
        f.write(json.dumps({"rank": 0, "attempt": 0, "step": 1,
                            "metrics": {"c": 2}}) + "\n")
    assert aggregate_worker_metrics(d) == {"c": 5}


def test_gang_status_rotates_and_serves_merged_snapshot(tmp_path):
    import urllib.request as rq
    from analytics_zoo_tpu.core.launcher import _GangMetricsServer

    class FakeProc:
        def poll(self):
            return None

    hb = tmp_path / "hb_w0"
    d = str(tmp_path / "m")
    status = _GangStatus(interval=0.0, metrics_dir=d, rotate_bytes=400)
    for step in range(6):
        hb.write_text(json.dumps({"step": step, "wall": time.time(),
                                  "metrics": {"train.steps": step}}))
        status.maybe_emit([FakeProc()], [str(hb)], attempt=0)
    # size rotation kicked in; every surviving line is whole
    assert os.path.exists(os.path.join(d, "metrics_w0.jsonl.1"))
    for path in glob.glob(os.path.join(d, "metrics_w0.jsonl*")):
        for line in open(path):
            json.loads(line)
    # gang_metrics.jsonl carries the merged snapshot
    lines = [json.loads(ln) for ln in
             open(os.path.join(d, "gang_metrics.jsonl"))]
    assert lines[-1]["metrics"]["train.steps"] == 5
    # and --metrics-port serves the same view as Prometheus text
    srv = _GangMetricsServer(0, status)
    try:
        text = rq.urlopen(f"http://127.0.0.1:{srv.port}/metrics",
                          timeout=10).read().decode()
        assert "zoo_train_steps 5" in text
    finally:
        srv.stop()


def test_export_jsonl_size_rotation(tmp_path):
    reg = MetricsRegistry()
    reg.counter("r.c").inc()
    path = str(tmp_path / "metrics.jsonl")
    for _ in range(50):
        reg.export_jsonl(path, max_bytes=2000)
    assert os.path.exists(path + ".1")
    assert os.path.getsize(path) < 4000  # bounded, not unbounded growth
    for p in (path, path + ".1"):
        for line in open(p):
            assert json.loads(line)["metrics"]["r.c"] == 1


# -- acceptance: hedged request reconstructs the span tree --------------------

@pytest.mark.faults
def test_hedged_request_tree_root_attempts_server_stages():
    """THE tracing acceptance: a request served through ReplicaSet with
    a hedge fired reconstructs root → (attempt spans per replica) →
    server-side assembly/inference/reply spans, live across two
    replicas."""
    ports = _two_ports()
    slow, fast = _Model(delay=0.4), _Model()
    s1 = ClusterServing(slow, port=ports[0], batch_size=1,
                        batch_timeout_ms=1).start()
    s2 = ClusterServing(fast, port=ports[1], batch_size=1,
                        batch_timeout_ms=1).start()
    rs = ReplicaSet([f"{s1.host}:{s1.port}", f"{s2.host}:{s2.port}"],
                    hedge_ms=50.0, start_health=False)
    try:
        tid = trace_lib.new_trace_id()
        out = rs.predict(np.arange(4, dtype=np.float32), deadline=5.0,
                         trace_id=tid, timeout=10.0)
        np.testing.assert_allclose(out, np.arange(4) * 2.0)
        # the losing (slow) attempt finishes its server-side work late:
        # poll until its stage spans landed in the ring
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            roots = trace_lib.tree(tid)
            if (len(roots) == 1
                    and len(roots[0].find("server.reply")) >= 2):
                break
            time.sleep(0.02)
        (root,) = trace_lib.tree(tid)
        assert root.name == "router"
        attempts = [c for c in root.children
                    if c.name in ("client", "client.attempt")]
        assert len(attempts) == 2, [c.name for c in root.children]
        replicas = {c.record.stages["client.replica"] for c in attempts}
        assert replicas == {f"{s1.host}:{s1.port}",
                            f"{s2.host}:{s2.port}"}
        # the WINNER is the fast replica's sibling span
        winner = [c for c in attempts if c.name == "client"]
        assert winner and winner[0].record.stages["client.replica"] == \
            f"{s2.host}:{s2.port}"
        # every attempt hangs its own server-side stage spans
        for att in attempts:
            (batch,) = att.find("server.batch")
            stage_names = {c.name for c in batch.children}
            assert stage_names == {"server.assembly", "server.inference",
                                   "server.reply"}, stage_names
        # and the slow attempt's inference span shows the armed delay
        loser = [c for c in attempts if c.name == "client.attempt"][0]
        (inf,) = loser.find("server.inference")
        assert inf.record.stages["inference_ms"] >= 300.0
    finally:
        rs.close()
        s1.stop()
        s2.stop()


# -- acceptance: flight recorder on replica hard-kill -------------------------

@pytest.mark.faults
def test_replica_down_dump_names_in_flight_traces_zero_client_failures(
        _flight_dir):
    """THE flight-recorder acceptance: hard-killing a replica under
    load produces a dump naming the in-flight trace ids lost on that
    replica, with zero client-visible failures (the router absorbs the
    kill exactly as before)."""
    ports = _two_ports()
    doomed_faults = FaultRegistry()
    # one inference worker + a slow model: requests QUEUE on the doomed
    # replica, so the kill reliably catches work in flight
    doomed = ClusterServing(_Model(delay=0.25), port=ports[0],
                            batch_size=1, batch_timeout_ms=1,
                            inference_workers=1,
                            faults=doomed_faults).start()
    survivor = ClusterServing(_Model(), port=ports[1], batch_size=1,
                              batch_timeout_ms=1).start()
    rs = ReplicaSet([f"{doomed.host}:{doomed.port}",
                     f"{survivor.host}:{survivor.port}"],
                    query_timeout=30.0, start_health=False)
    stop_load = threading.Event()
    tids: list = []
    failures: list = []
    served: list = []
    tids_lock = threading.Lock()

    def load(i):
        x = np.full((4,), float(i), np.float32)
        while not stop_load.is_set():
            tid = trace_lib.new_trace_id()
            with tids_lock:
                tids.append(tid)
            try:
                out = rs.predict(x, trace_id=tid, deadline=15.0,
                                 timeout=30.0)
            except Exception as e:  # noqa: BLE001 — the failure record
                failures.append(f"{type(e).__name__}: {e}")
                continue
            if out is None or not np.allclose(out, x * 2.0):
                failures.append("timeout/wrong answer")
            else:
                served.append(1)

    threads = [threading.Thread(target=load, args=(i,))
               for i in range(6)]
    for t in threads:
        t.start()
    try:
        time.sleep(0.4)  # steady state: the slow replica queues work
        assert not failures
        # the NEXT frame the doomed replica sees kills it — under
        # sustained load its queue holds in-flight requests right then
        doomed_faults.enable("serving.replica_down", times=1)
        deadline = time.monotonic() + 10
        while not doomed._stop.is_set():
            assert time.monotonic() < deadline, "kill fault never fired"
            time.sleep(0.01)
        time.sleep(0.5)  # load keeps flowing through the survivor
    finally:
        stop_load.set()
        for t in threads:
            t.join(timeout=30)
        doomed_faults.disable("serving.replica_down")
        rs.close()
        survivor.stop()
        doomed.stop()
    # zero client-visible failures — the original HA contract holds
    assert failures == [], failures[:5]
    assert served
    # the kill dumped a flight record naming the dying replica's
    # in-flight requests (a later breaker-open dump may have rotated it
    # to .1 — search both generations)
    base = os.path.join(_flight_dir, f"flightrec_{os.getpid()}.json")
    dumps = [json.load(open(p)) for p in (base, base + ".1")
             if os.path.exists(p)]
    kills = [d for d in dumps
             if d["reason"] == "serving.replica_down"]
    assert kills, [d["reason"] for d in dumps]
    ctx = kills[0]["context"]
    assert ctx["replica"] == f"{doomed.host}:{doomed.port}"
    lost = set(ctx["in_flight_traces"])
    assert lost, "no in-flight trace ids recorded at kill time"
    assert lost <= set(tids), "dump names requests we never sent"


def test_dump_flight_record_on_demand(_flight_dir):
    srv = ClusterServing(_Model(), batch_size=4).start()
    try:
        inq = InputQueue(port=srv.port)
        outq = OutputQueue(input_queue=inq)
        uid = inq.enqueue("t", t=np.ones((4,), np.float32))
        assert outq.query(uid, timeout=30) is not None
        path = srv.dump_flight_record()
        assert path and os.path.exists(path)
        dump = json.load(open(path))
        assert dump["reason"] == "on_demand"
        assert dump["context"]["state"] == "serving"
        # the served request's spans are in the dumped ring
        tid = inq.trace_id(uid) or ""
        names = {s["name"] for s in dump["spans"]}
        assert "server.batch" in names
        # counters moved since the recorder's baseline
        assert dump["metrics_delta"].get("server.replies", 0) >= 1
        inq.close()
    finally:
        srv.stop()


def test_estimator_dumps_flight_record_on_nonfinite_loss(tmp_path):
    from analytics_zoo_tpu.core import faults
    from analytics_zoo_tpu.orca.learn import Estimator, NonFiniteLossError
    init_orca_context("local")
    rng = np.random.default_rng(0)
    model_dir = str(tmp_path / "ckpt")
    est = Estimator.from_keras(nn.Sequential([nn.Dense(1)]), loss="mse",
                               learning_rate=1e-3, nan_policy="raise",
                               model_dir=model_dir)
    x = rng.normal(size=(64, 4)).astype(np.float32)
    y = rng.normal(size=(64, 1)).astype(np.float32)
    with faults.get_registry().armed("step.nan", times=1):
        with pytest.raises(NonFiniteLossError):
            est.fit((x, y), epochs=1, batch_size=32, verbose=False)
    path = os.path.join(model_dir, f"flightrec_{os.getpid()}.json")
    assert os.path.exists(path)
    dump = json.load(open(path))
    assert dump["reason"] == "train.NonFiniteLossError"
    assert dump["context"]["step"] >= 1


# -- cluster-scope scrape -----------------------------------------------------

def test_cluster_scope_scrape_merges_replica_registries():
    """Two replicas with PRIVATE registries: /metrics?scope=cluster
    folds both over the TCP metrics frame, replica labels dropped."""
    m1, m2 = MetricsRegistry(), MetricsRegistry()
    s1 = ClusterServing(_Model(), batch_size=4, metrics=m1).start()
    s2 = ClusterServing(_Model(), batch_size=4, metrics=m2).start()
    rs = ReplicaSet([f"{s1.host}:{s1.port}", f"{s2.host}:{s2.port}"],
                    start_health=False)
    fe = HTTPFrontend(router=rs).start()
    try:
        # drive traffic to EACH replica directly (the router would
        # least-pending everything onto one)
        for srv, n in ((s1, 2), (s2, 3)):
            inq = InputQueue(port=srv.port)
            outq = OutputQueue(input_queue=inq)
            for i in range(n):
                uid = inq.enqueue("t", t=np.full((4,), float(i),
                                                 np.float32))
                assert outq.query(uid, timeout=30) is not None
            inq.close()
        merged = rs.cluster_metrics()
        assert merged["server.requests"] == 5   # 2 + 3
        assert merged["server.replies"] == 5
        assert merged["server.inference_ms"]["count"] >= 2
        url = f"http://{fe.host}:{fe.port}"
        with urllib.request.urlopen(url + "/metrics?scope=cluster",
                                    timeout=10) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode()
        assert "zoo_server_requests 5" in text
        assert "zoo_server_replies 5" in text
        # the plain process scrape is unchanged by the new route
        with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
            assert "# TYPE" in r.read().decode()
    finally:
        fe.stop()
        s1.stop()
        s2.stop()


# -- step profiler ------------------------------------------------------------

def test_step_profiler_mfu_compiles_and_fit_span_tree():
    from analytics_zoo_tpu.orca.learn import Estimator
    init_orca_context("local", config=ZooConfig(device_peak_flops=1e9))
    rng = np.random.default_rng(0)
    x = rng.normal(size=(128, 4)).astype(np.float32)
    y = rng.normal(size=(128, 1)).astype(np.float32)
    model = nn.Sequential([nn.Dense(8, activation="relu"), nn.Dense(1)])
    est = Estimator.from_keras(model, loss="mse", learning_rate=1e-3,
                               profile={"flops_per_sample": 1e6})
    est.fit((x, y), epochs=2, batch_size=32, verbose=False)
    snap = metrics_lib.get_registry().snapshot()
    # compile events: the first step's XLA compile was detected
    assert snap["train.compiles"] >= 1
    assert est.compile_count >= 1
    # MFU: flops_per_sample × samples/s ÷ (peak × devices) — positive
    # and consistent with the declared analytics
    mfu = snap["train.mfu"]["value"]
    assert mfu > 0
    assert snap["train.mfu"]["max"] >= mfu
    # the fit's span tree: train.fit → train.epoch ×2 → train.step ×4
    (root,) = trace_lib.tree(est.trace_id)
    assert root.name == "train.fit"
    epochs = [c for c in root.children if c.name == "train.epoch"]
    assert len(epochs) == 2
    for ep in epochs:
        steps = [c for c in ep.children if c.name == "train.step"]
        assert len(steps) == 4
        assert all("data_wait_ms" in s.record.stages for s in steps)
    compiles = root.find("train.compile")
    assert len(compiles) >= 1


def test_profiler_off_registers_no_profiler_series():
    from analytics_zoo_tpu.orca.learn import Estimator
    init_orca_context("local")
    rng = np.random.default_rng(0)
    est = Estimator.from_keras(nn.Sequential([nn.Dense(1)]), loss="mse",
                               learning_rate=1e-3)
    est.fit((rng.normal(size=(64, 4)).astype(np.float32),
             rng.normal(size=(64, 1)).astype(np.float32)),
            epochs=1, batch_size=32, verbose=False)
    snap = metrics_lib.get_registry().snapshot()
    # profiler series may linger (zeroed) from another test's pinned
    # handles on the process-global registry — what matters is that an
    # unprofiled fit never MOVES them
    assert snap.get("train.mfu", {"value": 0})["value"] == 0
    assert snap.get("train.compiles", 0) == 0


def test_heartbeat_embeds_registry_snapshot_when_supervised(
        tmp_path, monkeypatch):
    """The worker half of gang aggregation: with ZOO_HEARTBEAT_METRICS
    set (the supervisor exports it next to --metrics-dir), epoch-end
    heartbeat payloads carry the full registry snapshot the supervisor
    folds into the gang view."""
    from analytics_zoo_tpu.orca.learn import Estimator
    monkeypatch.setenv("ZOO_HEARTBEAT_METRICS", "1")
    hb = tmp_path / "hb"
    init_orca_context("local", config=ZooConfig(heartbeat_file=str(hb),
                                                heartbeat_interval=0.0))
    rng = np.random.default_rng(0)
    est = Estimator.from_keras(nn.Sequential([nn.Dense(1)]), loss="mse",
                               learning_rate=1e-3)
    est.fit((rng.normal(size=(64, 4)).astype(np.float32),
             rng.normal(size=(64, 1)).astype(np.float32)),
            epochs=1, batch_size=32, verbose=False)
    payload = json.loads(hb.read_text())
    snap = payload["metrics"]
    assert snap["train.steps"] == 2
    assert snap["train.step_ms"]["count"] == 2
    # the payload is exactly what _fold_gang_snapshots consumes
    merged = _fold_gang_snapshots({(0, 0): snap, (1, 0): snap})
    assert merged["train.steps"] == 4


# -- feed decode spans --------------------------------------------------------

def test_streaming_feed_records_decode_spans():
    from analytics_zoo_tpu.data.stream import StreamingDataFeed
    mesh = init_orca_context("local")

    def load(i, rng=None):
        return {"x": np.full((4,), float(i), np.float32)}

    feed = StreamingDataFeed(num_samples=32, load_sample=load,
                             batch_size=8, shuffle=False, num_workers=2)
    n = sum(1 for _ in feed.epoch(mesh, 0))
    assert n == 4
    assert feed.trace_id is not None
    (root,) = trace_lib.tree(feed.trace_id)
    assert root.name == "feed.epoch"
    decodes = [c for c in root.children if c.name == "feed.decode"]
    assert len(decodes) == 4
    assert {c.record.stages["step"] for c in decodes} == {0, 1, 2, 3}


# -- overhead guard (serving) -------------------------------------------------

@pytest.mark.slow
def test_serving_span_and_metrics_overhead_under_5_percent():
    """Acceptance: the full span+metrics instrumentation adds <5% to
    serving closed-loop throughput vs the kill switches off
    (registry.enabled=False + trace disabled).  Best-of-3 runs per
    mode; a small absolute slack absorbs CPU scheduling noise, same
    pattern as the PR-3 train-loop guard."""
    reg = metrics_lib.get_registry()
    srv = ClusterServing(_Model(), batch_size=8, batch_timeout_ms=1
                         ).start()
    inq = InputQueue(port=srv.port)
    outq = OutputQueue(input_queue=inq)
    x = np.ones((16,), np.float32)

    def closed_loop(n=300):
        best = float("inf")
        for _ in range(3):
            t0 = time.monotonic()
            for _i in range(n):
                uid = inq.enqueue("t", t=x)
                assert outq.query(uid, timeout=30) is not None
            best = min(best, time.monotonic() - t0)
        return best

    try:
        closed_loop(50)  # warm every code path
        reg.enabled = False
        trace_lib.enabled = False
        t_off = closed_loop()
        reg.enabled = True
        trace_lib.enabled = True
        t_on = closed_loop()
    finally:
        reg.enabled = True
        trace_lib.enabled = True
        inq.close()
        srv.stop()
    assert t_on <= t_off * 1.05 + 0.05, (t_on, t_off)
