"""Parallelism tests on the 8-device CPU mesh: real XLA collectives
(SURVEY.md §4's 'cluster in a box' pattern, TPU-native form)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from analytics_zoo_tpu.core import init_orca_context, get_mesh


def _normal(rng, shape):
    return jnp.asarray(rng.normal(size=shape), jnp.float32)


# -- sharding rules -----------------------------------------------------------

def test_tensor_parallel_rules_match_transformer_params(rng):
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.parallel import (infer_param_specs,
                                            tensor_parallel_rules)
    mesh = init_orca_context("local", mesh_shape={"data": 4, "model": 2})
    layer = nn.TransformerLayer(num_heads=4)
    x = _normal(rng, (2, 8, 64))
    variables = layer.init(jax.random.PRNGKey(0), x)
    specs = infer_param_specs(variables["params"], tensor_parallel_rules(),
                              mesh)
    flat = {jax.tree_util.keystr(p): s for p, s in
            jax.tree_util.tree_flatten_with_path(specs)[0]}
    qk = [k for k in flat if k.endswith("'wq']")]
    assert flat[qk[0]] == P(None, "model")
    wo = [k for k in flat if k.endswith("'wo']")]
    assert flat[wo[0]] == P("model")
    ffn1 = [k for k in flat if "ffn1" in k and k.endswith("'kernel']")]
    assert flat[ffn1[0]] == P(None, "model")
    ln = [k for k in flat if "ln1" in k and k.endswith("'gamma']")]
    assert flat[ln[0]] == P()


def test_rules_drop_axes_absent_from_mesh(rng):
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.parallel import (infer_param_specs,
                                            tensor_parallel_rules)
    mesh = init_orca_context("local", mesh_shape={"data": 8})  # no model axis
    layer = nn.Dense(16, name="ffn1")

    class Wrap(nn.Module):
        def forward(self, scope, x):
            return scope.child(layer, x, name="ffn1")

    variables = Wrap().init(jax.random.PRNGKey(0), _normal(rng, (2, 8)))
    specs = infer_param_specs(variables["params"], tensor_parallel_rules(),
                              mesh)
    leaves = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P))
    assert all(s == P() for s in leaves)


def test_tensor_parallel_matmul_matches_replicated(rng):
    """GSPMD-partitioned Dense (kernel sharded over model) must equal the
    replicated computation bit-for-bit-ish."""
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.parallel import shard_variables, ShardingRule
    mesh = init_orca_context("local", mesh_shape={"data": 2, "model": 4})
    dense = nn.Dense(32)
    x = _normal(rng, (8, 16))
    variables = dense.init(jax.random.PRNGKey(0), x)
    expect, _ = dense.apply(variables, x)
    sharded = shard_variables(variables,
                              [ShardingRule(r"kernel$", P(None, "model"))],
                              mesh)
    xs = jax.device_put(x, NamedSharding(mesh, P("data")))
    got, _ = jax.jit(lambda v, x: dense.apply(v, x))(sharded, xs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               atol=1e-5, rtol=1e-5)


# -- ring attention -----------------------------------------------------------

@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(rng, causal):
    from analytics_zoo_tpu.ops import mha_reference
    from analytics_zoo_tpu.parallel import ring_self_attention
    init_orca_context("local", mesh_shape={"data": 2, "seq": 4})
    q = _normal(rng, (2, 32, 2, 8))
    k = _normal(rng, (2, 32, 2, 8))
    v = _normal(rng, (2, 32, 2, 8))
    out = jax.jit(lambda q, k, v: ring_self_attention(q, k, v, causal=causal)
                  )(q, k, v)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_grads_flow(rng):
    from analytics_zoo_tpu.parallel import ring_self_attention
    from analytics_zoo_tpu.ops import mha_reference
    init_orca_context("local", mesh_shape={"seq": 8})
    q = _normal(rng, (1, 16, 2, 8))
    k = _normal(rng, (1, 16, 2, 8))
    v = _normal(rng, (1, 16, 2, 8))
    g_ring = jax.jit(jax.grad(lambda q: ring_self_attention(q, k, v).sum())
                     )(q)
    g_ref = jax.grad(lambda q: mha_reference(q, k, v).sum())(q)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref),
                               atol=5e-5, rtol=5e-5)


def test_ring_attention_no_seq_axis_fallback(rng):
    from analytics_zoo_tpu.ops import mha_reference
    from analytics_zoo_tpu.parallel import ring_self_attention
    init_orca_context("local", mesh_shape={"data": 8})
    q = _normal(rng, (1, 8, 2, 4))
    out = ring_self_attention(q, q, q, causal=True)
    ref = mha_reference(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


# -- MoE ----------------------------------------------------------------------

def test_moe_forward_and_aux_loss(rng):
    from analytics_zoo_tpu.parallel import MoE
    init_orca_context("local", mesh_shape={"data": 4, "expert": 2})
    moe = MoE(num_experts=4, hidden_mult=2, top_k=2, capacity_factor=2.0)
    x = _normal(rng, (4, 8, 16))
    variables = moe.init(jax.random.PRNGKey(0), x)
    out, state = jax.jit(lambda v, x: moe.apply(v, x))(variables, x)
    assert out.shape == x.shape
    assert float(state["aux_loss"]) > 0.5  # balanced routing → ≈1
    # with ample capacity and top-2 gating, outputs are not all zero
    assert float(jnp.abs(out).mean()) > 1e-4


def test_moe_expert_sharded_matches_replicated(rng):
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.parallel import (MoE, infer_param_specs,
                                            shard_variables,
                                            tensor_parallel_rules)
    mesh = init_orca_context("local", mesh_shape={"data": 2, "expert": 4})

    class WithMoE(nn.Module):
        def forward(self, scope, x):
            return scope.child(MoE(num_experts=4, hidden_mult=2, top_k=1,
                                   capacity_factor=4.0), x, name="moe")

    model = WithMoE()
    x = _normal(rng, (2, 4, 8))
    variables = model.init(jax.random.PRNGKey(0), x)
    expect, _ = model.apply(variables, x)
    rules = tensor_parallel_rules()
    # the expert dim must actually land on the expert axis (regression:
    # generic wo$ rule used to shadow the moe rule)
    specs = infer_param_specs(variables["params"], rules, mesh)
    assert specs["moe"]["wi"] == P("expert")
    assert specs["moe"]["wo"] == P("expert")
    sharded = shard_variables(variables, rules, mesh)
    got, _ = jax.jit(lambda v, x: model.apply(v, x))(sharded, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               atol=1e-5, rtol=1e-5)


def test_moe_respects_capacity(rng):
    from analytics_zoo_tpu.parallel import MoE
    init_orca_context("local")
    # capacity_factor tiny → most tokens dropped → output mostly zeros
    moe = MoE(num_experts=2, hidden_mult=1, top_k=1, capacity_factor=0.02)
    x = _normal(rng, (2, 32, 8))
    variables = moe.init(jax.random.PRNGKey(0), x)
    out, _ = moe.apply(variables, x)
    zero_rows = np.mean(np.abs(np.asarray(out)).sum(-1) < 1e-9)
    assert zero_rows > 0.5


# -- pipeline -----------------------------------------------------------------

def _mlp_stage():
    import analytics_zoo_tpu.nn as nn

    class Stage(nn.Module):
        def forward(self, scope, x):
            h = scope.child(nn.Dense(16, activation="relu"), x, name="fc1")
            return scope.child(nn.Dense(8), h, name="fc2")
    return Stage()


def test_pipeline_matches_sequential(rng):
    from analytics_zoo_tpu.parallel import pipeline_apply, stacked_stage_init
    mesh = init_orca_context("local", mesh_shape={"data": 2, "pipe": 4})
    stage = _mlp_stage()
    x = _normal(rng, (8, 8))

    def stage_init(r):
        return stage.init(r, x[:2])["params"]

    def apply_fn(params, xb):
        out, _ = stage.apply({"params": params}, xb)
        return out

    stacked = stacked_stage_init(stage_init, 4, jax.random.PRNGKey(0))
    # reference: run the 4 stages sequentially
    expect = x
    for i in range(4):
        p_i = jax.tree_util.tree_map(lambda l: l[i], stacked)
        expect = apply_fn(p_i, expect)
    got = jax.jit(lambda sp, x: pipeline_apply(apply_fn, sp, x,
                                               n_microbatches=4, mesh=mesh)
                  )(stacked, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               atol=1e-5, rtol=1e-5)


def test_pipeline_no_pipe_axis_falls_back(rng):
    from analytics_zoo_tpu.parallel import pipeline_apply, stacked_stage_init
    init_orca_context("local", mesh_shape={"data": 8})
    stage = _mlp_stage()
    x = _normal(rng, (4, 8))

    def stage_init(r):
        return stage.init(r, x)["params"]

    def apply_fn(params, xb):
        out, _ = stage.apply({"params": params}, xb)
        return out

    stacked = stacked_stage_init(stage_init, 3, jax.random.PRNGKey(1))
    got = pipeline_apply(apply_fn, stacked, x, n_microbatches=2)
    expect = x
    for i in range(3):
        p_i = jax.tree_util.tree_map(lambda l: l[i], stacked)
        expect = apply_fn(p_i, expect)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               atol=1e-5, rtol=1e-5)


def test_pipeline_is_differentiable(rng):
    from analytics_zoo_tpu.parallel import pipeline_apply, stacked_stage_init
    mesh = init_orca_context("local", mesh_shape={"pipe": 4, "data": 2})
    stage = _mlp_stage()
    x = _normal(rng, (8, 8))

    def stage_init(r):
        return stage.init(r, x[:2])["params"]

    def apply_fn(params, xb):
        out, _ = stage.apply({"params": params}, xb)
        return out

    stacked = stacked_stage_init(stage_init, 4, jax.random.PRNGKey(0))

    def loss(sp):
        return pipeline_apply(apply_fn, sp, x, n_microbatches=4,
                              mesh=mesh).sum()

    grads = jax.jit(jax.grad(loss))(stacked)
    gnorm = sum(float(jnp.abs(g).sum())
                for g in jax.tree_util.tree_leaves(grads))
    assert np.isfinite(gnorm) and gnorm > 0


# -- estimator integration ----------------------------------------------------

def test_estimator_tp_matches_dp_loss(rng):
    """Same model/seed trained one epoch under dp-replicated vs tp-sharded
    params: loss curves must agree (GSPMD partitioning is numerics-preserving
    up to fp reassociation)."""
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.core import stop_orca_context
    from analytics_zoo_tpu.orca.learn import Estimator

    class Tiny(nn.Module):
        def forward(self, scope, x):
            h = scope.child(nn.Dense(32, activation="relu", name="ffn1"),
                            x, name="ffn1")
            return scope.child(nn.Dense(4, name="ffn2"), h, name="ffn2")

    x = rng.normal(size=(32, 8)).astype(np.float32)
    y = rng.integers(0, 4, 32).astype(np.int32)
    losses = {}
    for mode, mesh_shape in [("dp", {"data": 8}),
                             ("tp", {"data": 4, "model": 2}),
                             ("fsdp", {"fsdp": 8})]:
        stop_orca_context()
        init_orca_context("local", mesh_shape=mesh_shape)
        est = Estimator.from_keras(Tiny(), loss="sparse_categorical_crossentropy",
                                   learning_rate=0.1, sharding=mode)
        hist = est.fit((x, y), epochs=2, batch_size=16, verbose=False)
        losses[mode] = hist["loss"]
    np.testing.assert_allclose(losses["dp"], losses["tp"], rtol=1e-4)
    np.testing.assert_allclose(losses["dp"], losses["fsdp"], rtol=1e-4)


def test_graft_dryrun_multichip():
    import __graft_entry__ as g
    g.dryrun_multichip(8)


def test_pipeline_multiple_stages_per_device(rng):
    """4 stages over pipe=2: each device applies its 2 stages sequentially
    (regression: stages used to be silently dropped)."""
    from analytics_zoo_tpu.parallel import pipeline_apply, stacked_stage_init
    mesh = init_orca_context("local", mesh_shape={"data": 4, "pipe": 2})
    stage = _mlp_stage()
    x = _normal(rng, (8, 8))

    def stage_init(r):
        return stage.init(r, x[:2])["params"]

    def apply_fn(params, xb):
        out, _ = stage.apply({"params": params}, xb)
        return out

    stacked = stacked_stage_init(stage_init, 4, jax.random.PRNGKey(0))
    expect = x
    for i in range(4):
        p_i = jax.tree_util.tree_map(lambda l: l[i], stacked)
        expect = apply_fn(p_i, expect)
    got = jax.jit(lambda sp, x: pipeline_apply(apply_fn, sp, x,
                                               n_microbatches=2, mesh=mesh)
                  )(stacked, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               atol=1e-5, rtol=1e-5)


def test_causal_plus_padding_mask_combined(rng):
    """causal=True with an explicit padding mask must apply BOTH (regression:
    causal used to be silently dropped)."""
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.nn.attention import causal_mask
    init_orca_context("local")
    x = _normal(rng, (2, 6, 16))
    pad = jnp.ones((2, 1, 1, 6)).at[:, :, :, -2:].set(0)  # last 2 padded
    mha = nn.MultiHeadAttention(num_heads=2, causal=True)
    variables = mha.init(jax.random.PRNGKey(0), x)
    got, _ = mha.apply(variables, x, mask=pad)
    combined = pad.astype(bool) & causal_mask(6)
    expect, _ = nn.MultiHeadAttention(num_heads=2).apply(variables, x,
                                                         mask=combined)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               atol=1e-6)


def test_seq_mesh_does_not_crash_on_label_shapes(rng):
    """Rank-2 labels / non-divisible feature dims must not be seq-sharded
    (regression: device_put used to crash)."""
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.orca.learn import Estimator
    init_orca_context("local", mesh_shape={"data": 2, "seq": 4})
    x = rng.normal(size=(8, 10)).astype(np.float32)   # 10 % 4 != 0
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]  # [B, 3] one-hot
    est = Estimator.from_keras(nn.Sequential([nn.Dense(3)]),
                               loss="categorical_crossentropy",
                               learning_rate=0.1)
    hist = est.fit((x, y), epochs=1, batch_size=8, verbose=False)
    assert np.isfinite(hist["loss"][0])


def test_estimator_sharded_save_load_roundtrip(rng, tmp_path):
    """load() must restore the tp/fsdp layout, not replicate (regression)."""
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.orca.learn import Estimator

    class Tiny(nn.Module):
        def forward(self, scope, x):
            return scope.child(nn.Dense(4, name="ffn2"), x, name="ffn2")

    mesh = init_orca_context("local", mesh_shape={"fsdp": 8})
    x = rng.normal(size=(16, 8)).astype(np.float32)
    y = rng.integers(0, 4, 16).astype(np.int32)
    est = Estimator.from_keras(Tiny(), loss="sparse_categorical_crossentropy",
                               learning_rate=0.1, sharding="fsdp")
    est.fit((x, y), epochs=1, batch_size=16, verbose=False)
    path = str(tmp_path / "ckpt")
    est.save(path)
    est2 = Estimator.from_keras(Tiny(),
                                loss="sparse_categorical_crossentropy",
                                learning_rate=0.1, sharding="fsdp")
    est2.load(path)
    kernel = est2._ts["params"]["ffn2"]["kernel"]
    spec = kernel.sharding.spec
    assert spec and spec[0] == "fsdp", spec
    # and it keeps training
    hist = est2.fit((x, y), epochs=1, batch_size=16, verbose=False)
    assert np.isfinite(hist["loss"][0])


def test_moe_router_gets_gradient_top1(rng):
    """top_k=1 router must receive task-loss gradient (regression: gate
    renormalization to 1.0 used to sever it)."""
    from analytics_zoo_tpu.parallel import MoE
    init_orca_context("local")
    moe = MoE(num_experts=4, hidden_mult=1, top_k=1, capacity_factor=2.0)
    x = _normal(rng, (2, 8, 16))
    variables = moe.init(jax.random.PRNGKey(0), x)

    def loss(params):
        out, _ = moe.apply({"params": params, "state": variables["state"]}, x)
        return jnp.square(out).sum()

    g = jax.grad(loss)(variables["params"])
    assert float(jnp.abs(g["gate"]).sum()) > 1e-3


def test_moe_trains_through_estimator_with_aux_loss(rng):
    """MoE inside the Estimator: stable state structure (scan-safe) and the
    aux loss participates in the objective (regressions)."""
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.orca.learn import Estimator
    from analytics_zoo_tpu.parallel import MoE
    init_orca_context("local", mesh_shape={"data": 4, "expert": 2})

    class MoEModel(nn.Module):
        def forward(self, scope, x):
            h = scope.child(nn.Dense(16), x, name="in")
            h = h[:, None, :]  # [B, 1, D] token dim for the MoE
            h = scope.child(MoE(num_experts=2, hidden_mult=1, top_k=1,
                                capacity_factor=2.0), h, name="moe")
            return scope.child(nn.Dense(2), h[:, 0], name="head")

    est = Estimator.from_keras(MoEModel(),
                               loss="sparse_categorical_crossentropy",
                               learning_rate=0.05, sharding="tp")
    x = rng.normal(size=(32, 8)).astype(np.float32)
    y = rng.integers(0, 2, 32).astype(np.int32)
    hist = est.fit((x, y), epochs=3, batch_size=16, verbose=False)
    assert np.isfinite(hist["loss"][-1])
    # aux loss is recorded in the state after stepping
    assert "aux_loss" in est._ts["state"]["moe"]


def test_tp_fsdp_composes(rng):
    """'tp+fsdp' must shard tp kernels over BOTH axes (regression: fsdp dim
    used to stay replicated)."""
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.orca.learn.estimator import _resolve_sharding_rules
    from analytics_zoo_tpu.parallel import infer_param_specs
    mesh = init_orca_context("local",
                             mesh_shape={"fsdp": 2, "model": 4})
    layer = nn.TransformerLayer(num_heads=4)
    variables = layer.init(jax.random.PRNGKey(0), _normal(rng, (2, 8, 64)))
    rules = _resolve_sharding_rules("tp+fsdp")
    specs = infer_param_specs(variables["params"], rules, mesh)
    flat = {jax.tree_util.keystr(p): s for p, s in
            jax.tree_util.tree_flatten_with_path(specs)[0]}
    wq = [v for k, v in flat.items() if k.endswith("'wq']")][0]
    assert wq == P("fsdp", "model")
    wo = [v for k, v in flat.items() if k.endswith("'wo']")][0]
    assert wo == P("model", "fsdp")


def test_causal_cross_attention_shapes(rng):
    """causal with kv length != query length must not crash (regression)."""
    import analytics_zoo_tpu.nn as nn
    init_orca_context("local")
    x = _normal(rng, (2, 4, 16))
    kv = _normal(rng, (2, 9, 16))
    mha = nn.MultiHeadAttention(num_heads=2, causal=True)
    variables = mha.init(jax.random.PRNGKey(0), x, kv=kv)
    out, _ = mha.apply(variables, x, kv=kv)
    assert out.shape == (2, 4, 16)
