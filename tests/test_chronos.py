"""Chronos tests (reference pattern: pyzoo/test/zoo/chronos — synthetic
random-walk series generated in the test file)."""

import numpy as np
import pandas as pd
import pytest

from analytics_zoo_tpu.core import init_orca_context


@pytest.fixture(autouse=True)
def _ctx():
    init_orca_context("local")
    yield


def _series_df(n=200, freq="h", seed=0):
    rng = np.random.default_rng(seed)
    ts = pd.date_range("2021-01-01", periods=n, freq=freq)
    value = np.sin(np.arange(n) / 12) + 0.1 * rng.normal(size=n)
    return pd.DataFrame({"datetime": ts, "value": value,
                         "extra": rng.normal(size=n)})


# -- TSDataset ----------------------------------------------------------------

def test_tsdataset_roll_shapes():
    from analytics_zoo_tpu.chronos import TSDataset
    ts = TSDataset.from_pandas(_series_df(), dt_col="datetime",
                               target_col="value",
                               extra_feature_col=["extra"])
    ts.roll(lookback=24, horizon=4)
    x, y = ts.to_numpy()
    assert x.shape == (200 - 24 - 4 + 1, 24, 2)
    assert y.shape == (200 - 24 - 4 + 1, 4, 1)
    # y windows follow the x windows
    np.testing.assert_allclose(y[0, 0, 0], ts.df["value"].iloc[24])


def test_tsdataset_impute_dedup_resample():
    from analytics_zoo_tpu.chronos import TSDataset
    df = _series_df(50)
    df.loc[5, "value"] = np.nan
    df = pd.concat([df, df.iloc[[10]]])  # duplicate timestamp
    ts = TSDataset.from_pandas(df, dt_col="datetime", target_col="value",
                               extra_feature_col=["extra"])
    ts.deduplicate().impute(mode="linear")
    assert len(ts.df) == 50
    assert not ts.df["value"].isna().any()
    ts.resample("2h")
    assert len(ts.df) == 25


def test_tsdataset_scale_roundtrip():
    from analytics_zoo_tpu.chronos import TSDataset
    ts = TSDataset.from_pandas(_series_df(), dt_col="datetime",
                               target_col="value",
                               extra_feature_col=["extra"])
    raw = ts.df["value"].to_numpy().copy()
    ts.scale("standard")
    assert abs(ts.df["value"].mean()) < 1e-6
    ts.roll(lookback=10, horizon=1)
    _, y = ts.to_numpy()
    unscaled = ts.unscale_numpy(y)
    np.testing.assert_allclose(unscaled[:, 0, 0], raw[10:], rtol=1e-5)


def test_tsdataset_dt_features_and_split():
    from analytics_zoo_tpu.chronos import TSDataset
    train, val, test = TSDataset.from_pandas(
        _series_df(100), dt_col="datetime", target_col="value",
        with_split=True, val_ratio=0.1, test_ratio=0.1)
    assert len(train.df) == 80 and len(val.df) == 10 and len(test.df) == 10
    train.gen_dt_feature(["HOUR", "IS_WEEKEND"])
    assert "HOUR" in train.df.columns
    assert "HOUR" in train.feature_col


# -- forecasters --------------------------------------------------------------

@pytest.mark.parametrize("name", ["lstm", "seq2seq", "tcn"])
def test_forecasters_fit_predict_save_load(name, tmp_path):
    from analytics_zoo_tpu.chronos import (LSTMForecaster, Seq2SeqForecaster,
                                           TCNForecaster, TSDataset)
    cls = {"lstm": LSTMForecaster, "seq2seq": Seq2SeqForecaster,
           "tcn": TCNForecaster}[name]
    ts = TSDataset.from_pandas(_series_df(), dt_col="datetime",
                               target_col="value")
    fc = cls.from_tsdataset(ts, past_seq_len=16, future_seq_len=2)
    hist = fc.fit(epochs=2, batch_size=32)
    assert hist["loss"][-1] < hist["loss"][0] * 2  # trains, no blow-up
    x, y = ts.to_numpy()
    pred = fc.predict(x[:8])
    assert pred.shape == (8, 2, 1)
    res = fc.evaluate((x, y))
    assert np.isfinite(res["mse"])
    path = str(tmp_path / name)
    fc.save(path)
    fc2 = cls(past_seq_len=16, future_seq_len=2, input_feature_num=1,
              output_feature_num=1)
    fc2.load(path)
    np.testing.assert_allclose(fc2.predict(x[:8]), pred, atol=1e-5)


def test_tcn_forecaster_actually_learns():
    from analytics_zoo_tpu.chronos import TCNForecaster, TSDataset
    ts = TSDataset.from_pandas(_series_df(400, seed=3), dt_col="datetime",
                               target_col="value")
    fc = TCNForecaster.from_tsdataset(ts, past_seq_len=24, future_seq_len=1,
                                      lr=5e-3)
    fc.fit(epochs=8, batch_size=64)
    x, y = ts.to_numpy()
    pred = fc.predict(x)
    mse = float(np.mean((pred - y) ** 2))
    var = float(np.var(y))
    assert mse < var * 0.5  # beats the mean predictor comfortably


# -- detectors ----------------------------------------------------------------

def test_threshold_detector():
    from analytics_zoo_tpu.chronos import ThresholdDetector
    y = np.zeros(100)
    y[37] = 10.0
    det = ThresholdDetector(ratio=0.02)
    idx = det.anomaly_indexes(y)
    assert 37 in idx


def test_ae_detector():
    from analytics_zoo_tpu.chronos import AEDetector
    rng = np.random.default_rng(0)
    y = np.sin(np.arange(300) / 5) + 0.01 * rng.normal(size=300)
    y[200] += 8.0
    det = AEDetector(roll_len=12, ratio=0.02, epochs=5)
    idx = det.anomaly_indexes(y)
    assert any(195 <= i <= 205 for i in idx)


def test_dbscan_detector():
    from analytics_zoo_tpu.chronos import DBScanDetector
    y = np.concatenate([np.random.default_rng(0).normal(0, 0.1, 100), [5.0]])
    idx = DBScanDetector(eps=0.3, min_samples=3).anomaly_indexes(y)
    assert 100 in idx


# -- AutoTS -------------------------------------------------------------------

def test_autots_search_and_pipeline(tmp_path):
    from analytics_zoo_tpu.automl import hp
    from analytics_zoo_tpu.chronos import AutoTSEstimator, TSDataset, TSPipeline
    ts = TSDataset.from_pandas(_series_df(240), dt_col="datetime",
                               target_col="value")
    auto = AutoTSEstimator(model=["lstm", "tcn"],
                           search_space={"lr": hp.choice([1e-2, 1e-3])},
                           past_seq_len=hp.choice([8, 16]),
                           future_seq_len=1, seed=0)
    pipeline = auto.fit(ts, epochs=2, batch_size=32, n_sampling=3)
    cfg = auto.get_best_config()
    assert cfg["model"] in ("lstm", "tcn")
    ts.roll(pipeline.config["past_seq_len"], 1)
    x, y = ts.to_numpy()
    pred = pipeline.predict(x[:4])
    assert pred.shape == (4, 1, 1)
    path = str(tmp_path / "pipeline")
    pipeline.save(path)
    loaded = TSPipeline.load(path)
    np.testing.assert_allclose(loaded.predict(x[:4]), pred, atol=1e-5)


def test_tsdataset_multi_id_roll_does_not_span_series():
    """Windows must not cross id boundaries (regression)."""
    from analytics_zoo_tpu.chronos import TSDataset
    ts1 = _series_df(50, seed=1).assign(station="a")
    ts2 = _series_df(50, seed=2).assign(station="b")
    df = pd.concat([ts1, ts2])
    ts = TSDataset.from_pandas(df, dt_col="datetime", target_col="value",
                               id_col="station")
    ts.roll(lookback=10, horizon=1)
    x, y = ts.to_numpy()
    # per-id: 50 - 10 - 1 + 1 = 40 windows each
    assert x.shape[0] == 80
    # first window of series b must equal rolling b alone
    tsb = TSDataset.from_pandas(ts2, dt_col="datetime", target_col="value")
    tsb.roll(lookback=10, horizon=1)
    xb, _ = tsb.to_numpy()
    np.testing.assert_allclose(x[40], xb[0])


def test_tspipeline_save_preserves_model_kwargs(tmp_path):
    """model_kwargs (searched architecture) must survive save/load
    (regression)."""
    from analytics_zoo_tpu.automl import hp
    from analytics_zoo_tpu.chronos import AutoTSEstimator, TSDataset, TSPipeline
    ts = TSDataset.from_pandas(_series_df(120), dt_col="datetime",
                               target_col="value")
    auto = AutoTSEstimator(model=["lstm"],
                           search_space={"hidden_dim": hp.choice([16])},
                           past_seq_len=8, future_seq_len=1)
    pipe = auto.fit(ts, epochs=1, batch_size=16, n_sampling=1)
    path = str(tmp_path / "p")
    pipe.save(path)
    loaded = TSPipeline.load(path)
    assert loaded.config["model_kwargs"]["hidden_dim"] == 16
    ts.roll(8, 1)
    x, _ = ts.to_numpy()
    np.testing.assert_allclose(loaded.predict(x[:2]), pipe.predict(x[:2]),
                               atol=1e-5)


def test_tspipeline_unscales_predictions(tmp_path):
    """ADVICE r1 (low): a scaled TSDataset's pipeline must return forecasts
    in the ORIGINAL space, and the scaler must survive save/load."""
    from analytics_zoo_tpu.chronos import AutoTSEstimator, TSDataset, TSPipeline
    df = _series_df(120)
    df["value"] = df["value"] * 100.0 + 500.0  # far from scaled space
    ts = TSDataset.from_pandas(df, dt_col="datetime", target_col="value")
    ts.scale("standard")
    auto = AutoTSEstimator(model=["lstm"], past_seq_len=8, future_seq_len=1)
    pipe = auto.fit(ts, epochs=1, batch_size=16, n_sampling=1)
    assert pipe.scaler is not None and pipe.scaler["type"] == "standard"
    ts.roll(8, 1)
    x, y = ts.to_numpy()
    pred = pipe.predict(x[:4])
    # unscaled forecasts live near the original magnitude (~500), far from
    # the model's scaled output range (|v| ~ 1)
    assert np.abs(pred).mean() > 50
    np.testing.assert_allclose(pipe.predict(x[:4], unscale=False),
                               (pred - pipe.scaler["mean"][0]) /
                               pipe.scaler["std"][0], rtol=1e-4)
    path = str(tmp_path / "p")
    pipe.save(path)
    loaded = TSPipeline.load(path)
    assert loaded.scaler == pipe.scaler
    np.testing.assert_allclose(loaded.predict(x[:4]), pred, atol=1e-4)
    m = loaded.evaluate((x[:8], y[:8]))
    assert "mse" in m and np.isfinite(m["mse"])


# -- MTNet + TCMF (VERDICT r1 missing #8) -------------------------------------

def test_mtnet_forecaster_fit_predict_save_load(tmp_path):
    from analytics_zoo_tpu.chronos import MTNetForecaster, TSDataset
    ts = TSDataset.from_pandas(_series_df(200), dt_col="datetime",
                               target_col="value")
    # (long_num + 1) * series_length = (3 + 1) * 6 = 24
    ts.roll(24, 2)
    x, y = ts.to_numpy()
    fc = MTNetForecaster(past_seq_len=24, future_seq_len=2,
                         input_feature_num=x.shape[-1],
                         output_feature_num=1, long_series_num=3,
                         cnn_hid_size=8, rnn_hid_size=8)
    hist = fc.fit((x, y), epochs=2, batch_size=32)
    assert np.isfinite(hist["loss"][-1])
    pred = fc.predict(x[:8])
    assert pred.shape == (8, 2, 1)
    m = fc.evaluate((x[:16], y[:16]))
    assert np.isfinite(m["loss"])
    path = str(tmp_path / "mtnet")
    fc.save(path)
    fc2 = MTNetForecaster(past_seq_len=24, future_seq_len=2,
                          input_feature_num=x.shape[-1],
                          output_feature_num=1, long_series_num=3,
                          cnn_hid_size=8, rnn_hid_size=8)
    fc2.est._ensure_initialized(np.asarray(x[:2], np.float32))
    fc2.load(path)
    np.testing.assert_allclose(fc2.predict(x[:4]), pred[:4], atol=1e-5)


def test_mtnet_rejects_bad_window():
    from analytics_zoo_tpu.chronos import MTNetForecaster
    with pytest.raises(ValueError, match="divisible"):
        MTNetForecaster(past_seq_len=25, future_seq_len=1,
                        input_feature_num=1, output_feature_num=1,
                        long_series_num=3)


def test_tcmf_forecaster_panel_round_trip(tmp_path):
    from analytics_zoo_tpu.chronos import TCMFForecaster
    rng = np.random.default_rng(0)
    # synthetic low-rank panel: 12 series driven by 2 latent waves
    t = np.arange(120)
    basis = np.stack([np.sin(t / 6.0), np.cos(t / 11.0)])      # [2, T]
    mix = rng.normal(size=(12, 2))
    y = mix @ basis + 0.05 * rng.normal(size=(12, 120))
    fc = TCMFForecaster(rank=4, y_iters=400, tcn_lookback=12,
                        num_channels_X=(8, 8))
    loss = fc.fit({"y": y}, epochs=3)
    assert np.isfinite(loss)
    # factorization must actually reconstruct the panel
    recon = fc.F @ fc.X
    assert np.mean((recon - y) ** 2) < 0.1
    pred = fc.predict(horizon=6)
    assert pred.shape == (12, 6)
    assert np.all(np.isfinite(pred))
    m = fc.evaluate({"y": y[:, -6:]})
    assert np.isfinite(m["mae"])
    path = str(tmp_path / "tcmf")
    fc.save(path)
    fc2 = TCMFForecaster.load(path)
    np.testing.assert_allclose(fc2.predict(horizon=6), pred, atol=1e-4)


def test_tcmf_distributed_matches_single_device(tmp_path):
    """TCMF sharded over the mesh's data axis (series dimension; X-grad
    psum inserted by GSPMD) must reproduce the single-device result —
    SURVEY §2.6's distributed TCMF row, done the TPU way."""
    from analytics_zoo_tpu.chronos import TCMFForecaster
    from analytics_zoo_tpu.core import init_orca_context, stop_orca_context
    from analytics_zoo_tpu.data import XShards
    rng = np.random.default_rng(1)
    t = np.arange(96)
    basis = np.stack([np.sin(t / 5.0), np.cos(t / 9.0)])
    mix = rng.normal(size=(16, 2))
    y = (mix @ basis + 0.05 * rng.normal(size=(16, 96))).astype(np.float32)

    def run(mesh_shape, data):
        stop_orca_context()
        init_orca_context("local", mesh_shape=mesh_shape)
        fc = TCMFForecaster(rank=3, y_iters=150, tcn_lookback=10,
                            num_channels_X=(8,))
        fc.fit(data, epochs=2)
        return fc

    single = run({"data": 1}, {"y": y})
    # distributed input: 4 XShards of 4 series each, 8-way device mesh
    shards = XShards([{"id": [f"s{i}" for i in range(off, off + 4)],
                       "y": y[off:off + 4]} for off in range(0, 16, 4)])
    dist = run({"data": 8}, shards)
    np.testing.assert_allclose(dist.F, single.F, atol=1e-4)
    np.testing.assert_allclose(dist.X, single.X, atol=1e-4)
    pred = dist.predict(horizon=5)
    parts = pred.collect()  # distributed fit -> per-shard predictions
    assert [p["id"][0] for p in parts] == ["s0", "s4", "s8", "s12"]
    got = np.concatenate([p["prediction"] for p in parts])
    want = single.predict(horizon=5)
    np.testing.assert_allclose(got, want, atol=1e-3)
    # save/load keeps the distributed predict contract (shard metadata
    # persisted) — r4 review finding
    dist.save(str(tmp_path / "tcmf_dist"))
    reloaded = TCMFForecaster.load(str(tmp_path / "tcmf_dist"))
    parts2 = reloaded.predict(horizon=5).collect()
    assert [p["id"][0] for p in parts2] == ["s0", "s4", "s8", "s12"]
    np.testing.assert_allclose(
        np.concatenate([p["prediction"] for p in parts2]), got, atol=1e-4)
    stop_orca_context()


def test_xshards_tsdataset_global_scaling_matches_single_frame():
    """Distributed scale must use GLOBAL statistics: per-shard scaling would
    give different numbers (reference: experimental XShardsTSDataset)."""
    from analytics_zoo_tpu.chronos import TSDataset, XShardsTSDataset
    rng = np.random.default_rng(0)
    frames = []
    for sid, base in (("a", 0.0), ("b", 100.0), ("c", -50.0)):
        frames.append(pd.DataFrame({
            "ts": pd.date_range("2026-01-01", periods=60, freq="h"),
            "id": sid,
            "value": (base + rng.normal(0, 1, 60)).astype(np.float64),
        }))
    full = pd.concat(frames, ignore_index=True)

    dist = XShardsTSDataset.from_pandas(full, dt_col="ts",
                                        target_col="value", id_col="id",
                                        num_shards=3)
    dist = dist.scale("standard")
    single = TSDataset.from_pandas(full, dt_col="ts", target_col="value",
                                   id_col="id").scale("standard")

    dist.roll(lookback=8, horizon=2)
    single.roll(8, 2)
    xd, yd = dist.to_numpy()
    xs, ys = single.to_numpy()
    assert xd.shape == xs.shape and yd.shape == ys.shape
    # same global scaler → identical values (row order may differ by shard;
    # compare sorted flattened)
    np.testing.assert_allclose(np.sort(xd.ravel()), np.sort(xs.ravel()),
                               rtol=1e-6)
    # unscale round-trips
    back = dist.unscale_numpy(yd)
    assert back.std() > 10  # original spread restored


def test_xshards_tsdataset_to_feed_and_forecaster():
    from analytics_zoo_tpu.chronos import LSTMForecaster, XShardsTSDataset
    rng = np.random.default_rng(1)
    df = pd.DataFrame({
        "ts": np.tile(pd.date_range("2026-01-01", periods=50, freq="h"), 2),
        "id": np.repeat(["x", "y"], 50),
        "value": rng.normal(size=100).astype(np.float64),
    })
    ds = XShardsTSDataset.from_pandas(df, dt_col="ts", target_col="value",
                                      id_col="id", num_shards=2)
    ds = ds.impute().scale("minmax")
    ds.roll(lookback=10, horizon=1)
    x, y = ds.to_numpy()
    assert x.shape[1:] == (10, 1) and y.shape[1:] == (1, 1)
    fc = LSTMForecaster(past_seq_len=10, future_seq_len=1,
                        input_feature_num=1, output_feature_num=1)
    fc.fit((x, y), epochs=1, batch_size=16)
    assert fc.predict(x[:4]).shape == (4, 1, 1)


def test_xshards_scale_with_nans_matches_single_frame():
    from analytics_zoo_tpu.chronos import TSDataset, XShardsTSDataset
    rng = np.random.default_rng(3)
    vals = rng.normal(10, 2, 90)
    vals[::7] = np.nan  # pre-impute scaling must use non-NaN counts
    df = pd.DataFrame({
        "ts": np.tile(pd.date_range("2026-01-01", periods=30, freq="h"), 3),
        "id": np.repeat(["a", "b", "c"], 30),
        "value": vals,
    })
    dist = XShardsTSDataset.from_pandas(df, dt_col="ts",
                                        target_col="value", id_col="id",
                                        num_shards=3).scale("standard")
    single = TSDataset.from_pandas(df, dt_col="ts", target_col="value",
                                   id_col="id").scale("standard")
    np.testing.assert_allclose(float(dist.scaler["mean"]["value"]),
                               float(single.scaler["mean"]["value"]),
                               rtol=1e-9)
    np.testing.assert_allclose(float(dist.scaler["std"]["value"]),
                               float(single.scaler["std"]["value"]),
                               rtol=1e-9)


def test_xshards_roll_drops_short_shards():
    from analytics_zoo_tpu.chronos import XShardsTSDataset
    rng = np.random.default_rng(4)
    frames = {
        "long": pd.DataFrame({
            "ts": pd.date_range("2026-01-01", periods=40, freq="h"),
            "id": "long", "value": rng.normal(size=40)}),
        "short": pd.DataFrame({
            "ts": pd.date_range("2026-01-01", periods=5, freq="h"),
            "id": "short", "value": rng.normal(size=5)}),
    }
    df = pd.concat(frames.values(), ignore_index=True)
    ds = XShardsTSDataset.from_pandas(df, dt_col="ts", target_col="value",
                                      id_col="id", num_shards=2)
    ds.roll(lookback=8, horizon=2)
    x, y = ds.to_numpy()  # only the long shard contributes — no crash
    assert len(x) == 40 - 8 - 2 + 1


def test_xshards_gen_dt_feature_flows_into_roll():
    from analytics_zoo_tpu.chronos import TSDataset, XShardsTSDataset
    rng = np.random.default_rng(5)
    df = pd.DataFrame({
        "ts": pd.date_range("2026-01-01", periods=50, freq="h"),
        "value": rng.normal(size=50),
    })
    dist = XShardsTSDataset.from_pandas(df, dt_col="ts",
                                        target_col="value")
    dist.gen_dt_feature().roll(8, 1)
    xd, _ = dist.to_numpy()
    single = TSDataset.from_pandas(df, dt_col="ts", target_col="value")
    single.gen_dt_feature().roll(8, 1)
    xs, _ = single.to_numpy()
    assert xd.shape == xs.shape  # calendar features included, same as local
    assert xd.shape[-1] > 1


def test_xshards_scale_in_place():
    from analytics_zoo_tpu.chronos import XShardsTSDataset
    rng = np.random.default_rng(6)
    df = pd.DataFrame({
        "ts": pd.date_range("2026-01-01", periods=40, freq="h"),
        "value": rng.normal(1000.0, 5.0, 40),
    })
    ds = XShardsTSDataset.from_pandas(df, dt_col="ts", target_col="value")
    ds.scale("standard")  # TSDataset semantics: mutates, no reassignment
    ds.roll(8, 1)
    x, _ = ds.to_numpy()
    assert abs(float(x.mean())) < 1.0  # scaled, not raw ~1000


# -- ARIMA: executable in this image via the numpy backend (VERDICT r2 #5) ----

def test_arima_numpy_backend_recovers_ar1():
    from analytics_zoo_tpu.chronos.forecaster import ARIMAForecaster
    rng = np.random.default_rng(0)
    n, phi, c = 600, 0.7, 2.0
    y = np.zeros(n)
    for t in range(1, n):
        y[t] = c + phi * y[t - 1] + rng.normal(0, 0.5)
    f = ARIMAForecaster(order=(1, 0, 0), backend="numpy").fit(y)
    assert abs(f._fitted.phi[0] - phi) < 0.1
    # long-horizon forecasts approach the unconditional mean
    pred = f.predict(50)
    assert abs(pred[-1] - c / (1 - phi)) < 1.0


def test_arima_numpy_backend_d1_continues_trend():
    from analytics_zoo_tpu.chronos.forecaster import ARIMAForecaster
    rng = np.random.default_rng(1)
    slope = 0.5
    y = np.cumsum(slope + 0.05 * rng.normal(size=400))
    f = ARIMAForecaster(order=(0, 1, 0), backend="numpy").fit(y)
    pred = f.predict(5)
    np.testing.assert_allclose(np.diff(pred), slope, atol=0.05)
    assert abs(pred[0] - (y[-1] + slope)) < 0.1


def test_arima_numpy_backend_seasonal_differencing():
    from analytics_zoo_tpu.chronos.forecaster import ARIMAForecaster
    rng = np.random.default_rng(2)
    t = np.arange(480)
    y = 10 * np.sin(2 * np.pi * t / 12) + 0.1 * rng.normal(size=len(t))
    f = ARIMAForecaster(order=(1, 0, 0), seasonal_order=(0, 1, 0, 12),
                        backend="numpy").fit(y)
    pred = f.predict(12)
    true = 10 * np.sin(2 * np.pi * (t[-1] + 1 + np.arange(12)) / 12)
    assert np.abs(pred - true).mean() < 0.5


def test_arima_auto_backend_always_executes():
    """The auto backend must fit/predict in ANY image — statsmodels if
    importable, numpy otherwise (the round-2 'dead code' finding)."""
    from analytics_zoo_tpu.chronos.forecaster import ARIMAForecaster
    rng = np.random.default_rng(3)
    y = rng.normal(size=300).cumsum()
    f = ARIMAForecaster(order=(1, 1, 1)).fit(y)
    assert f.predict(4).shape == (4,)
    m = f.evaluate(y[-4:], horizon=4)
    assert set(m) == {"mse", "mae"}


def test_arima_numpy_backend_rejects_seasonal_arma():
    from analytics_zoo_tpu.chronos.forecaster import ARIMAForecaster
    with pytest.raises(NotImplementedError, match="statsmodels"):
        ARIMAForecaster(order=(1, 0, 0), seasonal_order=(1, 0, 0, 12),
                        backend="numpy").fit(np.arange(100.0))


# -- Prophet: executable in this image via the numpy backend ------------------

def test_prophet_numpy_backend_fits_trend_and_weekly_seasonality():
    from analytics_zoo_tpu.chronos.forecaster import ProphetForecaster
    rng = np.random.default_rng(0)
    ds = pd.date_range("2023-01-01", periods=400, freq="D")
    t = np.arange(400)
    y = (0.5 * t                                  # trend
         + 5.0 * np.sin(2 * np.pi * t / 7)        # weekly
         + 0.3 * rng.normal(size=400))
    f = ProphetForecaster(backend="numpy").fit(
        pd.DataFrame({"ds": ds, "y": y}))
    out = f.predict(horizon=14, freq="D")
    assert list(out.columns[:2]) == ["ds", "yhat"]
    t_fut = np.arange(400, 414)
    want = 0.5 * t_fut + 5.0 * np.sin(2 * np.pi * t_fut / 7)
    err = np.abs(out["yhat"].to_numpy() - want)
    assert err.mean() < 1.0, err


def test_prophet_auto_backend_always_executes():
    from analytics_zoo_tpu.chronos.forecaster import ProphetForecaster
    ds = pd.date_range("2024-01-01", periods=100, freq="D")
    f = ProphetForecaster().fit(
        pd.DataFrame({"ds": ds, "y": np.arange(100.0)}))
    assert f.backend in ("numpy", "prophet")
    out = f.predict(horizon=3, freq="D")
    assert len(out) == 3


def test_prophet_invalid_backend_rejected():
    from analytics_zoo_tpu.chronos.forecaster import ProphetForecaster
    with pytest.raises(ValueError, match="backend"):
        ProphetForecaster(backend="stan")


def test_prophet_numpy_backend_standard_kwargs_and_unsorted_ds():
    """Regression (r3 review): Prophet-convention kwargs translate (or
    reject clearly), and unsorted history is sorted like Prophet does."""
    from analytics_zoo_tpu.chronos.forecaster import ProphetForecaster
    rng = np.random.default_rng(0)
    ds = pd.date_range("2023-01-01", periods=300, freq="D")
    t = np.arange(300)
    y = 0.5 * t + 5 * np.sin(2 * np.pi * t / 7) + 0.1 * rng.normal(size=300)
    perm = rng.permutation(300)  # UNSORTED history
    df = pd.DataFrame({"ds": ds[perm], "y": y[perm]})
    f = ProphetForecaster(backend="numpy", weekly_seasonality=True,
                          n_changepoints=10).fit(df)
    out = f.predict(horizon=7, freq="D")
    # future dates start after the true max date
    assert out["ds"].iloc[0] > ds.max()
    t_fut = np.arange(300, 307)
    want = 0.5 * t_fut + 5 * np.sin(2 * np.pi * t_fut / 7)
    assert np.abs(out["yhat"].to_numpy() - want).mean() < 1.5
    with pytest.raises(ValueError, match="numpy"):
        ProphetForecaster(backend="numpy", seasonality_mode="multiplicative")


def test_prophet_numpy_explicit_seasonality_overrides_span_gate():
    """Regression (r3 review): weekly_seasonality=True must fit the weekly
    component even when the history covers < 2 weeks."""
    from analytics_zoo_tpu.chronos.forecaster import ProphetForecaster
    rng = np.random.default_rng(0)
    ds = pd.date_range("2024-01-01", periods=10 * 24, freq="h")  # 10 days
    t = np.arange(len(ds))
    y = 3.0 * np.sin(2 * np.pi * t / (7 * 24)) + 0.05 * rng.normal(
        size=len(t))
    f = ProphetForecaster(backend="numpy", weekly_seasonality=True,
                          n_changepoints=3).fit(
        pd.DataFrame({"ds": ds, "y": y}))
    out = f.predict(horizon=24, freq="h")
    t_fut = np.arange(len(t), len(t) + 24)
    want = 3.0 * np.sin(2 * np.pi * t_fut / (7 * 24))
    assert np.abs(out["yhat"].to_numpy() - want).mean() < 0.7


def test_autots_tsdataset_validation_rerolled_per_lookback():
    """Regression (r3 review): a TSDataset validation_data must be
    re-rolled per trial when lookback is a search dimension."""
    from analytics_zoo_tpu.automl import hp
    from analytics_zoo_tpu.chronos import AutoTSEstimator, TSDataset

    t_idx = pd.date_range("2024-01-01", periods=400, freq="h")
    rng = np.random.default_rng(0)
    df = pd.DataFrame({"timestamp": t_idx,
                       "value": np.sin(np.arange(400) / 10)
                       + 0.05 * rng.normal(size=400)})
    train, _, val = TSDataset.from_pandas(df, dt_col="timestamp",
                                          target_col="value",
                                          with_split=True, val_ratio=0.2,
                                          test_ratio=0.1)
    train.scale()
    val.scale(train.scaler, fit=False)
    auto = AutoTSEstimator(model=["lstm"],
                           past_seq_len=hp.choice([8, 16]),
                           future_seq_len=2)
    pipeline = auto.fit(train, validation_data=val, epochs=1,
                        n_sampling=3, max_concurrent=2)
    assert pipeline is not None
    assert all(t.status in ("done", "pruned") for t in auto.trials), \
        [(t.status, t.error) for t in auto.trials]


def test_tsdataset_to_torch_data_loader():
    torch = pytest.importorskip("torch")
    from analytics_zoo_tpu.chronos import TSDataset
    df = pd.DataFrame({"timestamp": pd.date_range("2024-01-01", periods=60,
                                                  freq="h"),
                       "value": np.arange(60.0)})
    ts = TSDataset.from_pandas(df, dt_col="timestamp", target_col="value")
    ts.roll(lookback=12, horizon=2)
    loader = ts.to_torch_data_loader(batch_size=8, shuffle=False)
    xb, yb = next(iter(loader))
    assert tuple(xb.shape) == (8, 12, 1) and tuple(yb.shape) == (8, 2, 1)
    assert isinstance(loader, torch.utils.data.DataLoader)


def test_auto_single_model_wrappers():
    from analytics_zoo_tpu.chronos import AutoLSTM, TSDataset
    t_idx = pd.date_range("2024-01-01", periods=300, freq="h")
    rng = np.random.default_rng(0)
    df = pd.DataFrame({"timestamp": t_idx,
                       "value": np.sin(np.arange(300) / 10)
                       + 0.05 * rng.normal(size=300)})
    train, _, _ = TSDataset.from_pandas(df, dt_col="timestamp",
                                        target_col="value",
                                        with_split=True, test_ratio=0.1)
    train.scale()
    with pytest.raises(ValueError, match="family"):
        AutoLSTM(model="tcn", past_seq_len=12, future_seq_len=2)
    auto = AutoLSTM(past_seq_len=12, future_seq_len=2)
    pipeline = auto.fit(train, epochs=1, n_sampling=2)
    assert pipeline is not None
    assert all(t.config["model"] == "lstm" for t in auto.trials)
