"""Scale-out training: quantized gradient collectives, 2D (data × model)
sharding from the Estimator, and large-batch optimizers (ROADMAP item 3;
PAPERS.md EQuARX + MLPerf-on-TPU-pods ladders).  Runs on the 8-device CPU
sim — real XLA collectives, no hardware."""

import logging
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from analytics_zoo_tpu.core import (MeshConfig, init_orca_context, metrics,
                                    stop_orca_context)
from analytics_zoo_tpu.core.context import make_mesh
from analytics_zoo_tpu.orca.learn import Estimator


def _mlp():
    import analytics_zoo_tpu.nn as nn
    return nn.Sequential([nn.Dense(32, activation="relu", name="ffn1"),
                          nn.Dense(4, name="ffn2")])


def _data(n=64, d=8, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.normal(size=(n, d)).astype(np.float32),
            rng.integers(0, classes, n).astype(np.int32))


def _flat_axes(spec):
    """Axis names appearing anywhere in a PartitionSpec."""
    out = []
    for e in spec:
        out.extend(e if isinstance(e, tuple) else ([e] if e else []))
    return out


def _fit(mesh_shape, epochs=2, **kw):
    stop_orca_context()
    init_orca_context("local", mesh_shape=mesh_shape)
    kw.setdefault("optimizer", "sgd")
    est = Estimator.from_keras(_mlp(),
                               loss="sparse_categorical_crossentropy",
                               learning_rate=0.1, seed=1, **kw)
    hist = est.fit(_data(), epochs=epochs, batch_size=32, verbose=False)
    return hist["loss"], est


# -- trim / fallback hardening ------------------------------------------------

def _fresh_fallbacks():
    from analytics_zoo_tpu.parallel.sharding import _reset_fallback_warnings
    _reset_fallback_warnings()


def test_non_dividing_dim_falls_back_with_warning_and_counter(caplog):
    """A rule whose mesh axis doesn't divide the tensor dim must replicate
    that dim (never error), WARN once, and count every occurrence."""
    from analytics_zoo_tpu.parallel import ShardingRule, infer_param_specs
    _fresh_fallbacks()
    mesh = init_orca_context("local", mesh_shape={"data": 4, "model": 2})
    params = {"odd": {"kernel": np.zeros((7, 3), np.float32)}}
    rules = [ShardingRule(r"kernel$", P("model", None))]
    with caplog.at_level(logging.WARNING, logger="analytics_zoo_tpu"):
        specs = infer_param_specs(params, rules, mesh)
        specs2 = infer_param_specs(params, rules, mesh)
    assert specs["odd"]["kernel"] == P()
    assert specs2["odd"]["kernel"] == P()
    warned = [r for r in caplog.records
              if "falling back to replication" in r.message]
    assert len(warned) == 1  # one-time per site, not per call
    snap = metrics.get_registry().snapshot()
    assert snap["train.sharding_fallbacks"] == 2  # counted every occurrence


def test_spec_longer_than_tensor_rank_falls_back(caplog):
    from analytics_zoo_tpu.parallel import ShardingRule, infer_param_specs
    _fresh_fallbacks()
    mesh = init_orca_context("local", mesh_shape={"data": 4, "model": 2})
    params = {"vec": {"bias": np.zeros((8,), np.float32)}}
    with caplog.at_level(logging.WARNING, logger="analytics_zoo_tpu"):
        specs = infer_param_specs(
            params, [ShardingRule(r"bias$", P(None, "model"))], mesh)
    assert specs["vec"]["bias"] == P()
    assert metrics.get_registry().snapshot()["train.sharding_fallbacks"] == 1
    assert any("has no such dim" in r.message for r in caplog.records)


def test_absent_axis_trims_silently(caplog):
    """Portability contract: a mesh that simply lacks the axis is NOT a
    fallback — no warning, no counter."""
    from analytics_zoo_tpu.parallel import (infer_param_specs,
                                            tensor_parallel_rules)
    _fresh_fallbacks()
    mesh = init_orca_context("local", mesh_shape={"data": 8})
    params = {"ffn1": {"kernel": np.zeros((8, 32), np.float32)}}
    with caplog.at_level(logging.WARNING, logger="analytics_zoo_tpu"):
        specs = infer_param_specs(params, tensor_parallel_rules(), mesh)
    assert specs["ffn1"]["kernel"] == P()
    snap = metrics.get_registry().snapshot()
    assert snap.get("train.sharding_fallbacks", 0) == 0
    assert not [r for r in caplog.records
                if "falling back" in r.message]


def test_rule_inference_on_nested_param_paths():
    """Patterns match the full /-joined path, so rules can pin one block's
    kernel while a generic rule covers the rest (first match wins)."""
    from analytics_zoo_tpu.parallel import ShardingRule, infer_param_specs
    mesh = init_orca_context("local", mesh_shape={"data": 4, "model": 2})
    params = {"encoder": {"block0": {"ffn1": {"kernel":
                                              np.zeros((8, 32), np.float32)}},
                          "block1": {"ffn1": {"kernel":
                                              np.zeros((8, 32), np.float32)}}},
              "head": {"kernel": np.zeros((32, 4), np.float32)}}
    rules = [ShardingRule(r"block1/ffn1/kernel$", P(None, "model")),
             ShardingRule(r"kernel$", P())]
    specs = infer_param_specs(params, rules, mesh)
    assert specs["encoder"]["block1"]["ffn1"]["kernel"] == P(None, "model")
    assert specs["encoder"]["block0"]["ffn1"]["kernel"] == P()
    assert specs["head"]["kernel"] == P()


def test_tp_and_fsdp_rule_specs_on_two_axis_mesh(rng):
    """tensor_parallel_rules / fsdp_rules spec correctness on the 2-axis
    data × model mesh the "2d" strategy builds."""
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.parallel import (fsdp_rules, infer_param_specs,
                                            tensor_parallel_rules)
    mesh = init_orca_context("local", mesh_shape="2d")
    assert dict(zip(mesh.axis_names, mesh.devices.shape)) == \
        {"data": 4, "model": 2}
    layer = nn.TransformerLayer(num_heads=4)
    variables = layer.init(jax.random.PRNGKey(0),
                           jnp.asarray(rng.normal(size=(2, 8, 64)),
                                       jnp.float32))
    specs = infer_param_specs(variables["params"],
                              tensor_parallel_rules(), mesh)
    flat = {jax.tree_util.keystr(p): s for p, s in
            jax.tree_util.tree_flatten_with_path(specs)[0]}
    assert [v for k, v in flat.items() if k.endswith("'wq']")][0] == \
        P(None, "model")
    assert [v for k, v in flat.items() if k.endswith("'wo']")][0] == \
        P("model")
    # fsdp rules on a mesh WITHOUT an fsdp axis trim to replication
    specs_f = infer_param_specs(variables["params"], fsdp_rules(), mesh)
    leaves = jax.tree_util.tree_leaves(
        specs_f, is_leaf=lambda x: isinstance(x, P))
    assert all(s == P() for s in leaves)


# -- 2D mesh + strategy -------------------------------------------------------

def test_mesh_for_strategy_layouts():
    assert MeshConfig.for_strategy("dp").resolved(8)["data"] == 8
    assert MeshConfig.for_strategy("fsdp").resolved(8)["fsdp"] == 8
    tp = MeshConfig.for_strategy("tp").resolved(8)
    assert tp["model"] == 8 and tp["data"] == 1
    d2 = MeshConfig.for_strategy("2d").resolved(8)
    assert d2 == {"data": 4, "fsdp": 1, "seq": 1, "pipe": 1, "model": 2,
                  "expert": 1}
    # degrade: model axis can't fit the device count → pure dp, no error
    assert MeshConfig.for_strategy("2d", n_devices=3).resolved(3)["model"] \
        == 1
    with pytest.raises(ValueError, match="unknown mesh strategy"):
        MeshConfig.for_strategy("3d")


def test_make_mesh_accepts_strategy_string():
    init_orca_context("local")  # device runtime up
    mesh = make_mesh("2d")
    assert mesh.axis_names == ("data", "model")
    assert mesh.devices.shape == (4, 2)


def test_estimator_2d_matches_dp_loss():
    """Estimator(sharding="2d") on the data × model mesh trains to
    numerical equivalence with dp on a fixed seed (GSPMD partitioning is
    numerics-preserving up to fp reassociation)."""
    dp, _ = _fit({"data": 8}, sharding="dp")
    d2, est = _fit("2d", sharding="2d")
    np.testing.assert_allclose(dp, d2, rtol=1e-4)
    # and the params really are model-sharded, not silently replicated
    kernels = [l for p, l in jax.tree_util.tree_flatten_with_path(
        est._ts["params"])[0] if "kernel" in jax.tree_util.keystr(p)]
    assert any("model" in _flat_axes(k.sharding.spec) for k in kernels)


def test_2d_checkpoint_save_restore_roundtrip(tmp_path):
    """2D-sharded variables round-trip: load() restores the data × model
    layout (not a silent replication) and training continues."""
    _, est = _fit("2d", sharding="2d", epochs=1)
    path = str(tmp_path / "ckpt2d")
    est.save(path)
    est2 = Estimator.from_keras(_mlp(),
                                loss="sparse_categorical_crossentropy",
                                optimizer="sgd", learning_rate=0.1,
                                seed=1, sharding="2d")
    est2.load(path)
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_flatten_with_path(est._ts["params"])[0],
            jax.tree_util.tree_flatten_with_path(est2._ts["params"])[0]):
        assert pa == pb
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        if "kernel" in jax.tree_util.keystr(pa):
            # rule-matched kernels keep the 2D layout through the
            # round-trip (unmatched leaves like biases may differ: the
            # compiled step's GSPMD propagation shards them to follow
            # their kernel, load places them per the rules — replicated)
            assert a.sharding.spec == b.sharding.spec
            assert "model" in _flat_axes(a.sharding.spec)
    hist = est2.fit(_data(), epochs=1, batch_size=32, verbose=False)
    assert np.isfinite(hist["loss"][0])


def test_2d_on_data_only_mesh_warns_and_trains_dp(caplog):
    with caplog.at_level(logging.WARNING, logger="analytics_zoo_tpu"):
        losses, _ = _fit({"data": 8}, sharding="2d", epochs=1)
    assert np.isfinite(losses[0])
    assert any("no sized model axis" in r.message for r in caplog.records)


# -- quantized gradient collectives -------------------------------------------

def test_grad_compression_none_is_bitwise_identical():
    """THE bisection guard: grad_compression="none" must reproduce the
    default dp loss history bit-for-bit (same compiled step, metering
    only) — same pattern as PR-4's prefetch equivalence test."""
    base, _ = _fit({"data": 8})
    none, _ = _fit({"data": 8}, grad_compression="none")
    assert base == none


def test_grad_compression_quantized_tracks_uncompressed():
    """bf16/int8 change only the gradient wire width: loss histories stay
    within the bench guard's tolerance of the uncompressed baseline."""
    base, _ = _fit({"data": 8})
    bf16, _ = _fit({"data": 8}, grad_compression="bf16")
    i8, est = _fit({"data": 8}, grad_compression="int8")
    assert abs(bf16[-1] - base[-1]) < 0.02
    assert abs(i8[-1] - base[-1]) < 0.02
    # int8 carries per-shard error-feedback residuals in the train state
    assert "ef" in est._ts
    ef0 = jax.tree_util.tree_leaves(est._ts["ef"])[0]
    assert ef0.shape[0] == 8  # one residual slice per batch shard
    assert float(np.abs(np.asarray(ef0)).sum()) > 0  # banked rounding error


def test_grad_bytes_and_comm_ms_metered():
    """train.grad_bytes asserts the ≥4× int8 wire cut; train.comm_ms
    records the per-epoch all-reduce probe."""
    _fit({"data": 8}, grad_compression="none", epochs=1)
    snap = metrics.get_registry().snapshot()
    none_bytes = snap["train.grad_bytes"]
    assert none_bytes > 0
    assert snap["train.comm_ms"]["count"] >= 1
    metrics.get_registry().reset()
    _fit({"data": 8}, grad_compression="int8", epochs=1)
    int8_bytes = metrics.get_registry().snapshot()["train.grad_bytes"]
    assert none_bytes / int8_bytes >= 4.0


def test_int8_error_feedback_checkpoints(tmp_path):
    _, est = _fit({"data": 8}, grad_compression="int8", epochs=1)
    path = str(tmp_path / "ckpt_ef")
    est.save(path)
    est2 = Estimator.from_keras(_mlp(),
                                loss="sparse_categorical_crossentropy",
                                optimizer="sgd", learning_rate=0.1,
                                seed=1, grad_compression="int8")
    est2.load(path)
    for a, b in zip(jax.tree_util.tree_leaves(est._ts["ef"]),
                    jax.tree_util.tree_leaves(est2._ts["ef"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    hist = est2.fit(_data(), epochs=1, batch_size=32, verbose=False)
    assert np.isfinite(hist["loss"][0])


def test_grad_compression_composes_with_2d():
    dp, _ = _fit({"data": 8}, epochs=1)
    d2, _ = _fit("2d", sharding="2d", grad_compression="int8", epochs=1)
    assert abs(d2[-1] - dp[-1]) < 0.02


def test_grad_compression_validation():
    init_orca_context("local")
    with pytest.raises(ValueError, match="grad_compression"):
        Estimator.from_keras(_mlp(), loss="mse", learning_rate=0.1,
                             grad_compression="fp4")
    with pytest.raises(ValueError, match="grad_accum"):
        Estimator.from_keras(_mlp(), loss="mse", learning_rate=0.1,
                             grad_compression="int8", grad_accum=2)


def test_compressed_allreduce_unit():
    """compressed_allreduce in isolation: int8 dequantized mean stays
    within one quantization step of the exact mean, and error feedback
    carries exactly the per-shard residual."""
    from analytics_zoo_tpu.parallel import compressed_allreduce
    rng = np.random.default_rng(3)
    g = jnp.asarray(rng.normal(size=(4, 16, 8)), jnp.float32)
    exact = np.asarray(g).mean(0)
    red, ef = compressed_allreduce({"w": g}, "int8")
    assert ef is not None
    # per-shard max-abs/127 scales: mean error bounded by one step
    step = np.abs(np.asarray(g)).max(axis=(1, 2)).mean() / 127.0
    assert np.abs(np.asarray(red["w"]) - exact).max() <= step
    # residual = what quantization dropped, per shard
    q_contrib = np.asarray(g) - np.asarray(ef["w"])
    np.testing.assert_allclose(q_contrib.mean(0), np.asarray(red["w"]),
                               rtol=1e-6, atol=1e-7)
    red_b, ef_b = compressed_allreduce({"w": g}, "bf16")
    assert ef_b is None
    assert np.abs(np.asarray(red_b["w"]) - exact).max() < 0.02


def test_grad_wire_bytes_analytics():
    from analytics_zoo_tpu.parallel import grad_wire_bytes
    params = {"k": np.zeros((10, 10), np.float32),
              "b": np.zeros((10,), np.float32)}
    assert grad_wire_bytes(params, None) == 440
    assert grad_wire_bytes(params, "none") == 440
    assert grad_wire_bytes(params, "bf16") == 220
    assert grad_wire_bytes(params, "int8") == 110


# -- large-batch optimizers (LARS / LAMB) -------------------------------------

def test_lars_trust_ratio_hand_computed():
    from analytics_zoo_tpu.orca.learn.optimizers import lars
    tx = lars(1.0, momentum=0.0, weight_decay=0.0,
              trust_coefficient=0.001)
    params = {"w": {"kernel": jnp.asarray([3.0, 4.0])}}
    grads = {"w": {"kernel": jnp.asarray([0.3, 0.4])}}
    state = tx.init(params)
    updates, _ = tx.update(grads, state, params)
    # ratio = 0.001 * ||w|| / ||g|| = 0.001 * 5 / 0.5 = 0.01
    np.testing.assert_allclose(np.asarray(updates["w"]["kernel"]),
                               [-0.003, -0.004], rtol=1e-5)


def test_lars_excludes_bias_and_norm_params():
    from analytics_zoo_tpu.orca.learn.optimizers import lars
    tx = lars(0.5, momentum=0.0, weight_decay=0.1,
              trust_coefficient=0.001)
    params = {"d": {"kernel": jnp.asarray([3.0, 4.0]),
                    "bias": jnp.asarray([1.0, 2.0]),
                    "gamma": jnp.asarray([1.0, 1.0])}}
    g = jnp.asarray([0.3, 0.4])
    grads = {"d": {"kernel": g, "bias": g, "gamma": g}}
    updates, _ = tx.update(grads, tx.init(params), params)
    # excluded leaves: plain -lr * g — no trust ratio, no weight decay
    np.testing.assert_allclose(np.asarray(updates["d"]["bias"]),
                               np.asarray(-0.5 * g), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(updates["d"]["gamma"]),
                               np.asarray(-0.5 * g), rtol=1e-6)
    # the kernel IS adapted (different from plain sgd)
    assert not np.allclose(np.asarray(updates["d"]["kernel"]),
                           np.asarray(-0.5 * g))


def test_lamb_trust_ratio_first_step():
    from analytics_zoo_tpu.orca.learn.optimizers import lamb
    tx = lamb(0.1, weight_decay=0.0, eps=1e-6)
    p = np.asarray([3.0, 4.0], np.float32)
    g = np.asarray([0.3, -0.4], np.float32)
    params = {"w": {"kernel": jnp.asarray(p)}}
    updates, _ = tx.update({"w": {"kernel": jnp.asarray(g)}},
                           tx.init(params), params)
    # step 1: m̂ = g, v̂ = g² → u = g/(|g|+eps) ≈ sign(g); ratio = ||p||/||u||
    u = g / (np.abs(g) + 1e-6)
    expect = -0.1 * (np.linalg.norm(p) / np.linalg.norm(u)) * u
    np.testing.assert_allclose(np.asarray(updates["w"]["kernel"]), expect,
                               rtol=1e-4)


def test_lamb_excluded_leaf_is_plain_adam():
    from analytics_zoo_tpu.orca.learn.optimizers import lamb
    tx = lamb(0.1, weight_decay=0.5, eps=1e-6)
    p = jnp.asarray([1.0, 2.0])
    g = np.asarray([0.3, -0.4], np.float32)
    params = {"d": {"bias": p}}
    updates, _ = tx.update({"d": {"bias": jnp.asarray(g)}},
                           tx.init(params), params)
    expect = -0.1 * g / (np.abs(g) + 1e-6)  # no decay, no ratio
    np.testing.assert_allclose(np.asarray(updates["d"]["bias"]), expect,
                               rtol=1e-4)


def test_lars_lamb_resolvable_by_name_and_train():
    from analytics_zoo_tpu.orca.learn import optimizers as opt_lib
    import optax
    for name in ("lars", "lamb"):
        tx = opt_lib.get(name, 0.01)
        assert isinstance(tx, optax.GradientTransformation)
    losses, _ = _fit({"data": 8}, optimizer="lamb", epochs=2)
    assert losses[-1] < losses[0]  # it actually optimizes


def test_lars_momentum_accumulates():
    from analytics_zoo_tpu.orca.learn.optimizers import lars
    tx = lars(1.0, momentum=0.9, weight_decay=0.0, trust_coefficient=1.0)
    params = {"kernel": jnp.asarray([1.0, 0.0])}
    grads = {"kernel": jnp.asarray([1.0, 0.0])}
    state = tx.init(params)
    u1, state = tx.update(grads, state, params)
    u2, _ = tx.update(grads, state, params)
    # second step carries 0.9 * first velocity on top of the fresh term
    assert abs(float(u2["kernel"][0])) > abs(float(u1["kernel"][0]))
