"""AutoML tests (reference pattern: pyzoo/test/zoo/orca/automl)."""

import numpy as np
import pytest

from analytics_zoo_tpu.core import init_orca_context


@pytest.fixture(autouse=True)
def _ctx():
    init_orca_context("local")
    yield


def test_hp_samplers_and_grid():
    from analytics_zoo_tpu.automl import hp
    rng = np.random.default_rng(0)
    space = {"a": hp.choice([1, 2, 3]), "b": hp.uniform(0.0, 1.0),
             "c": hp.randint(5, 10), "d": hp.loguniform(1e-4, 1e-1),
             "e": hp.quniform(0, 10, 2), "fixed": 7}
    for _ in range(20):
        s = hp.sample(space, rng)
        assert s["a"] in (1, 2, 3)
        assert 0.0 <= s["b"] <= 1.0
        assert 5 <= s["c"] < 10
        assert 1e-4 <= s["d"] <= 1e-1
        assert s["e"] % 2 == 0
        assert s["fixed"] == 7
    g = hp.grid({"a": hp.grid_search([1, 2]), "b": hp.choice(["x", "y"])})
    assert len(g) == 4


def test_random_search_finds_good_config():
    from analytics_zoo_tpu.automl import RandomSearchEngine, hp

    def trial(config, report):
        # quadratic bowl: best at x=3
        m = (config["x"] - 3.0) ** 2
        report(m, 1)
        return m

    eng = RandomSearchEngine(metric_mode="min", seed=0)
    best = eng.run(trial, {"x": hp.uniform(-10, 10)}, n_trials=40)
    assert abs(best.config["x"] - 3.0) < 2.0
    assert len(eng.trials) == 40


def test_asha_prunes_bad_trials():
    from analytics_zoo_tpu.automl import ASHAScheduler, RandomSearchEngine, hp

    def trial(config, report):
        for step in range(1, 10):
            report(config["level"], step)
        return config["level"]

    sched = ASHAScheduler(metric_mode="min", grace_period=1,
                          reduction_factor=3, max_t=9)
    eng = RandomSearchEngine(metric_mode="min", scheduler=sched, seed=1)
    best = eng.run(trial, {"level": hp.uniform(0, 1)}, n_trials=12)
    pruned = [t for t in eng.trials if t.status == "pruned"]
    assert len(pruned) > 0            # bad trials stopped early
    assert best.metric == min(t.metric for t in eng.trials
                              if t.metric is not None)


def test_search_survives_failing_trials():
    from analytics_zoo_tpu.automl import RandomSearchEngine, hp

    def trial(config, report):
        if config["x"] < 0:
            raise RuntimeError("boom")
        return config["x"]

    eng = RandomSearchEngine(metric_mode="min", seed=0)
    best = eng.run(trial, {"x": hp.uniform(-1, 1)}, n_trials=16)
    assert best.metric is not None and best.metric >= 0
    assert any(t.status == "error" for t in eng.trials)


def test_auto_estimator_end_to_end(rng):
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.automl import AutoEstimator, hp

    x = rng.normal(size=(64, 8)).astype(np.float32)
    w = rng.normal(size=(8, 1)).astype(np.float32)
    y = x @ w

    def creator(config):
        return nn.Sequential([nn.Dense(config["hidden"], activation="relu"),
                              nn.Dense(1)])

    auto = AutoEstimator.from_keras(creator, loss="mse", metric="mse")
    auto.fit((x, y), epochs=2, batch_size=16, n_sampling=3,
             search_space={"hidden": hp.choice([4, 8]),
                           "lr": hp.choice([1e-2, 1e-3])})
    cfg = auto.get_best_config()
    assert cfg["hidden"] in (4, 8)
    est = auto.get_best_estimator()
    assert est.evaluate((x, y), batch_size=16)["mse"] < 10.0


def test_auto_estimator_asha_string():
    import numpy as np
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.automl import AutoEstimator, hp

    def model_fn(config):
        return nn.Sequential([nn.Dense(int(config["units"]),
                                       activation="relu"), nn.Dense(1)])

    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 4)).astype(np.float32)
    y = x.sum(axis=1, keepdims=True).astype(np.float32)
    auto = AutoEstimator(model_fn, loss="mse")
    auto.fit((x, y), epochs=2, batch_size=16, n_sampling=3,
             search_space={"units": hp.choice([8, 16]),
                           "lr": hp.loguniform(1e-3, 1e-1)},
             scheduler="asha")
    assert auto.get_best_model() is not None


def test_trials_run_concurrently():
    """>=2 trials genuinely overlap with max_concurrent=2 (VERDICT r2 #7;
    reference RayTuneSearchEngine ran parallel Tune workers)."""
    import threading
    import time as _time
    from analytics_zoo_tpu.automl.search import RandomSearchEngine
    from analytics_zoo_tpu.automl import hp

    active = [0]
    peak = [0]
    lock = threading.Lock()

    def trial_fn(config, report):
        with lock:
            active[0] += 1
            peak[0] = max(peak[0], active[0])
        _time.sleep(0.2)
        with lock:
            active[0] -= 1
        return config["x"]

    eng = RandomSearchEngine(metric_mode="min", max_concurrent=2, seed=0)
    best = eng.run(trial_fn, {"x": hp.uniform(0, 1)}, n_trials=4)
    assert peak[0] >= 2, f"never overlapped (peak={peak[0]})"
    assert best.metric == min(t.metric for t in eng.trials)


def test_autots_accepts_max_concurrent():
    import numpy as np
    import pandas as pd
    from analytics_zoo_tpu.chronos import AutoTSEstimator, TSDataset

    t_idx = pd.date_range("2024-01-01", periods=300, freq="h")
    rng = np.random.default_rng(0)
    df = pd.DataFrame({"timestamp": t_idx,
                       "value": np.sin(np.arange(300) / 10)
                       + 0.05 * rng.normal(size=300)})
    train, _, _ = TSDataset.from_pandas(df, dt_col="timestamp",
                                        target_col="value",
                                        with_split=True, test_ratio=0.1)
    train.scale()
    auto = AutoTSEstimator(model=["lstm"], past_seq_len=12,
                           future_seq_len=2)
    pipeline = auto.fit(train, epochs=1, n_sampling=2, max_concurrent=2)
    assert pipeline is not None and len(auto.trials) == 2


def test_autots_concurrent_trials_with_varied_lookback():
    """Regression (r3 review): concurrent trials with DIFFERENT lookback
    candidates must not corrupt each other's rolled windows."""
    import numpy as np
    import pandas as pd
    from analytics_zoo_tpu.automl import hp
    from analytics_zoo_tpu.chronos import AutoTSEstimator, TSDataset

    t_idx = pd.date_range("2024-01-01", periods=400, freq="h")
    rng = np.random.default_rng(0)
    df = pd.DataFrame({"timestamp": t_idx,
                       "value": np.sin(np.arange(400) / 10)
                       + 0.05 * rng.normal(size=400)})
    train, _, _ = TSDataset.from_pandas(df, dt_col="timestamp",
                                        target_col="value",
                                        with_split=True, test_ratio=0.1)
    train.scale()
    auto = AutoTSEstimator(model=["lstm"],
                           past_seq_len=hp.choice([8, 16, 24]),
                           future_seq_len=2)
    pipeline = auto.fit(train, epochs=1, n_sampling=4, max_concurrent=3)
    assert pipeline is not None
    # every trial completed (a window-shape race raises inside fit)
    assert all(t.status in ("done", "pruned") for t in auto.trials), \
        [(t.status, t.error) for t in auto.trials]


def test_fit_args_apply_to_preexisting_engine():
    """Regression (r3 review): max_concurrent/scheduler on fit() must take
    effect when an engine already exists (custom engine or second fit)."""
    import numpy as np
    from analytics_zoo_tpu.automl import AutoEstimator, hp
    from analytics_zoo_tpu.automl.search import (ASHAScheduler,
                                                 GridSearchEngine)
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.core import init_orca_context

    init_orca_context("local")
    eng = GridSearchEngine(metric_mode="min")
    auto = AutoEstimator(lambda cfg: nn.Sequential([nn.Dense(2)]),
                         loss="sparse_categorical_crossentropy",
                         search_engine=eng)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 4)).astype(np.float32)
    y = rng.integers(0, 2, 32).astype(np.int32)
    sched = ASHAScheduler(metric_mode="min")
    auto.fit((x, y), epochs=1, n_sampling=2,
             search_space={"lr": hp.choice([1e-3, 1e-2])},
             scheduler=sched, max_concurrent=2)
    assert eng.max_concurrent == 2
    assert eng.scheduler is sched


def test_trial_timeout_does_not_wedge_search():
    """A trial that blows its wall-clock budget is marked
    status="timeout"; the search completes on the other trials."""
    import time as _time
    from analytics_zoo_tpu.automl import GridSearchEngine, hp

    def trial(config, report):
        if config["x"] == 0:
            _time.sleep(3.0)  # never reports: only the hard wall can stop it
        return float(config["x"])

    eng = GridSearchEngine(metric_mode="min", trial_timeout_s=0.4)
    best = eng.run(trial, {"x": hp.choice([0, 1, 2])}, n_trials=3)
    statuses = {t.config["x"]: t.status for t in eng.trials}
    assert statuses[0] == "timeout"
    assert statuses[1] == statuses[2] == "done"
    assert best.metric == 1.0
    slow = next(t for t in eng.trials if t.config["x"] == 0)
    assert slow.duration_s < 2.5  # returned at the wall, not after sleep


def test_trial_timeout_cooperative_via_report():
    """A trial that reports hits the cooperative deadline check and is
    stopped from inside (keeping its partial metric)."""
    import time as _time
    from analytics_zoo_tpu.automl import RandomSearchEngine, hp

    def trial(config, report):
        for step in range(100):
            _time.sleep(0.05)
            report(10.0 - step, step)
        return 0.0

    eng = RandomSearchEngine(metric_mode="min", trial_timeout_s=0.3,
                             seed=0)
    # the timed-out trial keeps its best reported metric, so the search
    # still returns it as a scored result
    best = eng.run(trial, {"x": hp.uniform(0, 1)}, n_trials=1)
    t = eng.trials[0]
    assert t.status == "timeout"
    assert t.history  # partial reports retained
    assert t.metric == min(t.history)
    assert best is t


def test_trial_transient_failure_retried():
    from analytics_zoo_tpu.automl import RandomSearchEngine, hp
    attempts = {}

    def trial(config, report):
        key = round(config["x"], 6)
        attempts[key] = attempts.get(key, 0) + 1
        if attempts[key] == 1:
            raise ConnectionError("transient blip")
        return config["x"]

    eng = RandomSearchEngine(metric_mode="min", trial_retries=1, seed=0)
    best = eng.run(trial, {"x": hp.uniform(0, 1)}, n_trials=4)
    assert best.metric is not None
    for t in eng.trials:
        assert t.status == "done"
        assert t.retries == 1  # one transient failure absorbed each


def test_trial_retry_budget_exhausted_is_error():
    from analytics_zoo_tpu.automl import RandomSearchEngine, hp

    def trial(config, report):
        raise RuntimeError("always broken")

    eng = RandomSearchEngine(metric_mode="min", trial_retries=2, seed=0)
    with pytest.raises(RuntimeError, match="all 2 trials failed"):
        eng.run(trial, {"x": hp.uniform(0, 1)}, n_trials=2)
    for t in eng.trials:
        assert t.status == "error"
        assert t.retries == 2
