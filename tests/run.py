"""Sharded test runner: each test module in its own pytest process.

Reference pattern (SURVEY.md §4.2): the reference never ran its suite in
one process either — ``pyzoo/dev/run-pytests*.sh`` sharded pytest into
separate invocations because in-process state conflicts across frameworks.
The analog here: 370+ tests in a single interpreter accumulate jit
executables / native-queue / TB-writer state and can abort the interpreter
deep into the run (round-3 finding), while every module is green standalone.
One process per module bounds that state by construction.

Usage:
    python -m tests.run                # full suite, sequential
    python -m tests.run test_nn data   # only modules matching a substring
    python -m tests.run --failfast     # stop at first failing module

Exit code 0 iff every module's pytest run passes.  ``dev/run-pytests.sh``
is the shell-facing wrapper.
"""

from __future__ import annotations

import argparse
import glob
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Per-module wall-clock leash (seconds).  The heavyweights get more; a hang
# (compile-service stall, deadlocked queue) is reported as a failure with
# the faulthandler dump instead of wedging the whole run.
DEFAULT_TIMEOUT = 600
TIMEOUTS = {
    "test_models": 1200, "test_examples": 1200, "test_parallel": 1200,
    "test_net": 900, "test_chronos": 900, "test_automl": 900,
    "test_docs": 900, "test_multihost": 900,
}

_TAIL = re.compile(r"(\d+) (passed|failed|error|errors|skipped|xfailed|"
                   r"xpassed|warnings?|deselected)")


def _modules(patterns):
    mods = sorted(glob.glob(os.path.join(REPO, "tests", "test_*.py")))
    if patterns:
        mods = [m for m in mods
                if any(p in os.path.basename(m) for p in patterns)]
    return mods


def _run_module(path: str) -> dict:
    name = os.path.splitext(os.path.basename(path))[0]
    timeout = TIMEOUTS.get(name, DEFAULT_TIMEOUT)
    cmd = [sys.executable, "-m", "pytest", path, "-q", "--no-header",
           # dump all thread stacks if a test wedges (leaves 60s for
           # pytest teardown before our subprocess leash fires)
           "-o", f"faulthandler_timeout={timeout - 60}",
           # an unregistered marker is a silent tier-1 filter bypass
           # (`-m 'not slow'` can't deselect a typo'd mark) — fail fast
           "-W", "error::pytest.PytestUnknownMarkWarning"]
    t0 = time.perf_counter()
    # Popen + communicate (not subprocess.run): on timeout, run() discards
    # the pipe contents, losing the faulthandler dump this runner exists
    # to surface — communicate()'s second attempt reads what's buffered.
    # Own session + killpg: several modules spawn grandchildren (2-process
    # jax.distributed, preemption workers) that inherit the stdout pipe;
    # killing only pytest would leave communicate() blocked on them.
    proc = subprocess.Popen(cmd, cwd=REPO, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True,
                            start_new_session=True)
    try:
        out, _ = proc.communicate(timeout=timeout)
        rc = proc.returncode
    except subprocess.TimeoutExpired:
        import signal
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        try:
            out, _ = proc.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            out = ""
        rc = -1
        out = (out or "") + f"\n<<runner: module timed out after {timeout}s>>"
    dt = time.perf_counter() - t0
    counts = {kind: int(num) for num, kind in _TAIL.findall(
        "\n".join(out.splitlines()[-5:]))}
    # pytest rc 5 = "no tests collected": tolerate (e.g. all skipped by
    # importorskip at collection), but surface it in the summary
    ok = rc == 0 or rc == 5
    return {"name": name, "rc": rc, "ok": ok, "seconds": dt,
            "passed": counts.get("passed", 0),
            "failed": counts.get("failed", 0) + counts.get("error", 0),
            "skipped": counts.get("skipped", 0), "output": out}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("patterns", nargs="*",
                        help="substring filters on module names")
    parser.add_argument("--failfast", "-x", action="store_true")
    args = parser.parse_args(argv)

    mods = _modules(args.patterns)
    if not mods:
        print(f"no test modules match {args.patterns}", file=sys.stderr)
        return 2
    results = []
    t0 = time.perf_counter()
    for i, path in enumerate(mods, 1):
        name = os.path.splitext(os.path.basename(path))[0]
        print(f"[{i:2d}/{len(mods)}] {name} ...", end="", flush=True)
        r = _run_module(path)
        results.append(r)
        status = "ok" if r["ok"] else f"FAIL(rc={r['rc']})"
        print(f" {status}  {r['passed']} passed"
              + (f", {r['failed']} failed" if r["failed"] else "")
              + (f", {r['skipped']} skipped" if r["skipped"] else "")
              + f"  [{r['seconds']:.1f}s]", flush=True)
        if not r["ok"]:
            tail = "\n".join(r["output"].splitlines()[-40:])
            print(f"----- {name} output tail -----\n{tail}\n"
                  f"----- end {name} -----", flush=True)
            if args.failfast:
                break
    total = time.perf_counter() - t0
    n_pass = sum(r["passed"] for r in results)
    n_fail = sum(r["failed"] for r in results)
    n_skip = sum(r["skipped"] for r in results)
    bad = [r["name"] for r in results if not r["ok"]]
    slowest = sorted(results, key=lambda r: -r["seconds"])[:5]
    print(f"\n{len(results)} modules in {total:.0f}s: "
          f"{n_pass} passed, {n_fail} failed, {n_skip} skipped")
    print("slowest: " + ", ".join(f"{r['name']} {r['seconds']:.0f}s"
                                  for r in slowest))
    if bad:
        print("FAILED modules: " + ", ".join(bad))
        return 1
    print("ALL GREEN")
    return 0


if __name__ == "__main__":
    sys.exit(main())
