"""NN layer tests: shapes, purity, state handling, differential goldens."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import analytics_zoo_tpu.nn as nn

KEY = jax.random.PRNGKey(0)


def test_dense_shapes_and_grad():
    layer = nn.Dense(16, activation="relu")
    x = jnp.ones((4, 8))
    variables = layer.init(KEY, x)
    y, _ = layer.apply(variables, x)
    assert y.shape == (4, 16)
    assert variables["params"]["kernel"].shape == (8, 16)

    def loss(v):
        out, _ = layer.apply(v, x)
        return (out ** 2).mean()
    g = jax.grad(loss)(variables)
    assert g["params"]["kernel"].shape == (8, 16)
    assert float(jnp.abs(g["params"]["kernel"]).sum()) > 0


def test_dense_matches_numpy():
    layer = nn.Dense(3, use_bias=True)
    x = np.random.default_rng(0).normal(size=(5, 4)).astype(np.float32)
    variables = layer.init(KEY, jnp.asarray(x))
    w = np.asarray(variables["params"]["kernel"])
    b = np.asarray(variables["params"]["bias"])
    y, _ = layer.apply(variables, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(y), x @ w + b, rtol=1e-5)


def test_sequential_lenet_forward():
    model = nn.Sequential([
        nn.Conv2D(6, 5, padding="same", activation="relu"),
        nn.MaxPooling2D(2),
        nn.Conv2D(16, 5, padding="valid", activation="relu"),
        nn.MaxPooling2D(2),
        nn.Flatten(),
        nn.Dense(120, activation="relu"),
        nn.Dense(84, activation="relu"),
        nn.Dense(10),
    ])
    x = jnp.ones((2, 28, 28, 1))
    variables, y = model.init_apply(KEY, x)
    assert y.shape == (2, 10)
    assert nn.param_count(variables) > 40000


def test_conv2d_matches_known():
    # 1x1 kernel conv == per-pixel dense
    layer = nn.Conv2D(2, 1, use_bias=False)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(1, 4, 4, 3)),
                    jnp.float32)
    variables = layer.init(KEY, x)
    w = np.asarray(variables["params"]["kernel"])[0, 0]  # [3, 2]
    y, _ = layer.apply(variables, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x) @ w, rtol=1e-5,
                               atol=1e-6)


def test_pooling():
    x = jnp.arange(16, dtype=jnp.float32).reshape(1, 4, 4, 1)
    ymax, _ = nn.MaxPooling2D(2).init_apply(KEY, x)[1], None
    y, _ = nn.MaxPooling2D(2).apply({}, x)
    np.testing.assert_array_equal(np.asarray(y)[0, :, :, 0],
                                  [[5, 7], [13, 15]])
    ya, _ = nn.AveragePooling2D(2).apply({}, x)
    np.testing.assert_allclose(np.asarray(ya)[0, :, :, 0],
                               [[2.5, 4.5], [10.5, 12.5]])


def test_batchnorm_state_updates():
    bn = nn.BatchNormalization(momentum=0.5)
    x = jnp.asarray(np.random.default_rng(0).normal(3.0, 2.0, (64, 8)),
                    jnp.float32)
    variables = bn.init(KEY, x, training=True)
    assert np.allclose(variables["state"]["mean"], 0.0)
    y, new_state = bn.apply(variables, x, training=True)
    # output normalized in training mode
    assert abs(float(y.mean())) < 1e-4
    # running stats moved toward batch stats
    assert float(np.abs(new_state["mean"]).sum()) > 0.1
    # eval mode uses running stats, returns unchanged state
    variables2 = {"params": variables["params"], "state": new_state}
    y2, state2 = bn.apply(variables2, x, training=False)
    np.testing.assert_allclose(np.asarray(state2["mean"]),
                               np.asarray(new_state["mean"]))


def test_dropout_modes():
    d = nn.Dropout(0.5)
    x = jnp.ones((100, 100))
    y_eval, _ = d.apply({}, x, training=False)
    np.testing.assert_array_equal(np.asarray(y_eval), np.asarray(x))
    y_train, _ = d.apply({}, x, training=True, rng=KEY)
    frac_zero = float((np.asarray(y_train) == 0).mean())
    assert 0.4 < frac_zero < 0.6
    # needs rng in training mode
    with pytest.raises(ValueError):
        d.apply({}, x, training=True)


def test_layernorm():
    ln = nn.LayerNormalization()
    x = jnp.asarray(np.random.default_rng(0).normal(5, 3, (4, 10)), jnp.float32)
    variables = ln.init(KEY, x)
    y, _ = ln.apply(variables, x)
    np.testing.assert_allclose(np.asarray(y.mean(-1)), 0.0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(y.std(-1)), 1.0, atol=1e-2)


def test_embedding():
    emb = nn.Embedding(10, 4)
    ids = jnp.asarray([[1, 2], [3, 4]])
    variables, y = emb.init_apply(KEY, ids)
    assert y.shape == (2, 2, 4)
    table = np.asarray(variables["params"]["embeddings"])
    np.testing.assert_allclose(np.asarray(y)[0, 0], table[1])


def test_lstm_shapes_and_determinism():
    lstm = nn.LSTM(12, return_sequences=True)
    x = jnp.ones((3, 7, 5))
    variables, y = lstm.init_apply(KEY, x)
    assert y.shape == (3, 7, 12)
    last = nn.LSTM(12)
    v2, y2 = last.init_apply(KEY, x)
    assert y2.shape == (3, 12)
    y2b, _ = last.apply(v2, x)
    np.testing.assert_array_equal(np.asarray(y2), np.asarray(y2b))


def test_gru_and_simplernn():
    x = jnp.ones((2, 5, 3))
    for cls in (nn.GRU, nn.SimpleRNN):
        _, y = cls(6).init_apply(KEY, x)
        assert y.shape == (2, 6)


def test_lstm_gradient_flows_through_time():
    lstm = nn.LSTM(4)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 6, 3)),
                    jnp.float32)
    variables = lstm.init(KEY, x)

    def loss(v, xx):
        out, _ = lstm.apply(v, xx)
        return (out ** 2).sum()
    gx = jax.grad(loss, argnums=1)(variables, x)
    # gradient reaches the first timestep
    assert float(jnp.abs(gx[:, 0]).sum()) > 0


def test_bidirectional_concat():
    bi = nn.Bidirectional(nn.LSTM(5, return_sequences=True))
    x = jnp.ones((2, 4, 3))
    _, y = bi.init_apply(KEY, x)
    assert y.shape == (2, 4, 10)


def test_time_distributed():
    td = nn.TimeDistributed(nn.Dense(7))
    x = jnp.ones((2, 4, 3))
    _, y = td.init_apply(KEY, x)
    assert y.shape == (2, 4, 7)


def test_mha_self_attention():
    mha = nn.MultiHeadAttention(num_heads=4)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 6, 16)),
                    jnp.float32)
    variables, y = mha.init_apply(KEY, x)
    assert y.shape == (2, 6, 16)


def test_mha_masking_blocks_future():
    mha = nn.MultiHeadAttention(num_heads=2)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(1, 5, 8)), jnp.float32)
    causal = jnp.tril(jnp.ones((1, 1, 5, 5)))
    variables = mha.init(KEY, x, mask=causal)
    y1, _ = mha.apply(variables, x, mask=causal)
    # perturb the last token: outputs for earlier positions must not change
    x2 = x.at[0, -1].add(10.0)
    y2, _ = mha.apply(variables, x2, mask=causal)
    np.testing.assert_allclose(np.asarray(y1)[0, :4], np.asarray(y2)[0, :4],
                               atol=1e-5)
    assert not np.allclose(np.asarray(y1)[0, 4], np.asarray(y2)[0, 4])


def test_transformer_layer():
    block = nn.TransformerLayer(num_heads=4)
    x = jnp.ones((2, 6, 32))
    variables, y = block.init_apply(KEY, x)
    assert y.shape == (2, 6, 32)


def test_losses_golden():
    logits = jnp.asarray([[2.0, 0.0], [0.0, 2.0]])
    labels = jnp.asarray([0, 1])
    val = float(nn.losses.sparse_categorical_crossentropy(logits, labels))
    expected = -np.log(np.exp(2) / (np.exp(2) + 1))
    np.testing.assert_allclose(val, expected, rtol=1e-5)

    np.testing.assert_allclose(
        float(nn.losses.mean_squared_error(jnp.asarray([1.0, 3.0]),
                                           jnp.asarray([0.0, 0.0]))), 5.0)
    # bce from logits matches explicit formula
    lp = jnp.asarray([0.3, -1.2])
    lt = jnp.asarray([1.0, 0.0])
    p = 1 / (1 + np.exp(-np.asarray(lp)))
    expected = -np.mean(np.asarray(lt) * np.log(p) +
                        (1 - np.asarray(lt)) * np.log(1 - p))
    np.testing.assert_allclose(
        float(nn.losses.binary_crossentropy(lp, lt)), expected, rtol=1e-5)


def test_metrics():
    acc = nn.metrics.get("accuracy")
    logits = jnp.asarray([[0.1, 0.9], [0.9, 0.1], [0.2, 0.8]])
    labels = jnp.asarray([1, 0, 0])
    stats = acc.update(logits, labels)
    assert float(acc.result(stats)) == pytest.approx(2 / 3)

    auc = nn.metrics.get("auc")
    rng = np.random.default_rng(0)
    scores = jnp.asarray(rng.normal(size=500) +
                         2 * np.repeat([0, 1], 250).astype(np.float32))
    labels = jnp.asarray(np.repeat([0, 1], 250))
    val = float(auc.result(auc.update(scores, labels)))
    assert 0.85 < val < 1.0


def test_unknown_names_raise():
    with pytest.raises(ValueError):
        nn.activations.get("not_a_thing")
    with pytest.raises(ValueError):
        nn.losses.get("not_a_loss")
    with pytest.raises(ValueError):
        nn.metrics.get("not_a_metric")
    with pytest.raises(ValueError):
        nn.initializers.get("not_an_init")


def test_apply_is_pure():
    model = nn.Sequential([nn.Dense(4), nn.Dense(2)])
    x = jnp.ones((2, 3))
    variables = model.init(KEY, x)
    before = jax.tree_util.tree_map(np.asarray, variables)
    model.apply(variables, x)
    after = jax.tree_util.tree_map(np.asarray, variables)
    jax.tree_util.tree_map(np.testing.assert_array_equal, before, after)


def test_go_backwards_sees_full_sequence(rng):
    """go_backwards + return_sequences=False must return the end of the
    *backward* pass (a summary of the whole sequence), not a one-frame
    output (regression: code-review finding)."""
    import analytics_zoo_tpu.nn as nn
    x = jnp.asarray(rng.normal(size=(3, 7, 5)), jnp.float32)
    lstm = nn.LSTM(4, go_backwards=True, return_state=True)
    variables = lstm.init(jax.random.PRNGKey(0), x)
    (out, (h, c)), _ = lstm.apply(variables, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(h), rtol=1e-6)
    # and it must differ from running on just the last frame
    out1, _ = lstm.apply(variables, x[:, -1:, :])
    assert not np.allclose(np.asarray(out), np.asarray(out1[0]))


def test_bf16_dtype_preserved_through_stack(rng):
    """Dense/LayerNorm keep bf16 activations in bf16 (regression)."""
    import analytics_zoo_tpu.nn as nn
    x = jnp.asarray(rng.normal(size=(2, 8)), jnp.bfloat16)
    for layer in [nn.Dense(16), nn.LayerNormalization()]:
        variables = layer.init(jax.random.PRNGKey(0), x)
        y, _ = layer.apply(variables, x)
        assert y.dtype == jnp.bfloat16, type(layer).__name__


def test_lr_schedule_specs():
    from analytics_zoo_tpu.orca.learn import optimizers as opt
    sched = opt.resolve_learning_rate(
        {"schedule": "warmup_cosine", "peak": 1e-3, "warmup_steps": 10,
         "decay_steps": 100})
    assert abs(float(sched(10)) - 1e-3) < 1e-9  # peak after warmup
    assert float(sched(0)) == 0.0
    poly = opt.resolve_learning_rate(
        {"schedule": "poly", "lr": 1.0, "decay_steps": 10, "power": 1.0})
    assert abs(float(poly(5)) - 0.5) < 1e-6
    assert opt.resolve_learning_rate(3e-4) == 3e-4
    import pytest as _pytest
    with _pytest.raises(ValueError, match="unknown schedule"):
        opt.resolve_learning_rate({"schedule": "nope", "peak": 1e-3})
    # end-to-end: estimator accepts a schedule spec
    import numpy as _np
    import analytics_zoo_tpu.nn as _nn
    from analytics_zoo_tpu.orca.learn import Estimator
    est = Estimator.from_keras(
        _nn.Sequential([_nn.Dense(1)]), loss="mse",
        learning_rate={"schedule": "warmup_cosine", "peak": 1e-2,
                       "warmup_steps": 2, "decay_steps": 20})
    x = _np.ones((16, 4), _np.float32)
    hist = est.fit((x, _np.zeros((16, 1), _np.float32)), epochs=2,
                   batch_size=8, verbose=False)
    assert _np.isfinite(hist["loss"][-1])


def test_module_summary():
    import analytics_zoo_tpu.nn as _nn
    import jax as _jax
    model = _nn.Sequential([_nn.Dense(16, activation="relu", name="fc1"),
                            _nn.Dense(2, name="fc2")])
    x = jnp.ones((4, 8))
    variables = model.init(_jax.random.PRNGKey(0), x)
    text = model.summary(variables, x, print_fn=None)
    assert "fc1" in text and "fc2" in text
    assert "(4, 16)" in text and "(4, 2)" in text
    assert "total params:" in text


def test_module_summary_execution_order():
    import analytics_zoo_tpu.nn as _nn
    import jax as _jax
    # names chosen so lexicographic != execution order
    model = _nn.Sequential([_nn.Dense(4, name="zz_first"),
                            _nn.Dense(2, name="aa_second")])
    x = jnp.ones((2, 8))
    variables = model.init(_jax.random.PRNGKey(0), x)
    text = model.summary(variables, x, print_fn=None)
    assert text.index("zz_first") < text.index("aa_second")


def test_extended_loss_functions():
    from analytics_zoo_tpu.nn import losses
    yp = jnp.asarray([[2.0], [0.5]])
    yt = jnp.asarray([[1.0], [1.0]])
    assert float(losses.get("squared_hinge")(yp, yt)) >= 0.0
    mape = float(losses.get("mape")(yp, yt))
    np.testing.assert_allclose(mape, 100 * (1.0 + 0.5) / 2, rtol=1e-5)
    msle = float(losses.get("msle")(yp, yt))
    assert msle > 0
    poisson = float(losses.get("poisson")(yp, yt))
    np.testing.assert_allclose(
        poisson, float(np.mean([2 - np.log(2), 0.5 - np.log(0.5)])),
        rtol=1e-5)


def test_batchnorm_bf16_badly_centered_channels():
    """Regression (r3 review): BN on bf16 activations with |mean| >> std
    must normalize in f32 — bf16 x*scale would drown the signal."""
    rng = np.random.default_rng(0)
    # mean >> std but still representable in bf16 (quantum at 10 is
    # ~0.0625 < std): input keeps its signal, so any remaining error
    # comes from the normalize math itself
    x32 = (10.0 + 1.0 * rng.normal(size=(64, 8))).astype(np.float32)
    bn = nn.BatchNormalization(momentum=0.0, epsilon=1e-5)
    v = bn.init(jax.random.PRNGKey(0), jnp.asarray(x32), training=True)
    out16, _ = bn.apply(v, jnp.asarray(x32, jnp.bfloat16), training=True)
    out32, _ = bn.apply(v, jnp.asarray(x32), training=True)
    corr = np.corrcoef(np.asarray(out16, np.float32).ravel(),
                       np.asarray(out32).ravel())[0, 1]
    assert corr > 0.99, corr
    assert float(np.abs(np.asarray(out32).mean())) < 1e-3
    # r4 advisor: the bf16-rounded mean's bias must be COMPENSATED.  An
    # uncompensated bf16 mean injects a deterministic per-channel bias
    # of up to (|mean|/std)*2^-9 sigma (~0.02 here); the implementation
    # may center however it likes (exact f32 subtract, or the faster
    # bf16 subtract + f32 rounding-residual folded into the shift) as
    # long as the residual bias stays at rounding-noise level (~5e-4).
    ch_bias = np.abs(np.asarray(out16, np.float32).mean(axis=0))
    assert float(ch_bias.max()) < 5e-3, ch_bias


def test_batchnorm_badly_centered_channels():
    """Regression (r4 review): single-pass variance must not cancel for
    channels with |mean| >> std — the shifted-moments formulation keeps
    f32 precision where raw E[x^2]-E[x]^2 collapses."""
    rng = np.random.default_rng(0)
    x = (1e4 + rng.normal(size=(64, 8)).astype(np.float32))
    bn = nn.BatchNormalization()
    variables = bn.init(jax.random.PRNGKey(0), jnp.asarray(x),
                        training=True)
    out, state = bn.apply(variables, jnp.asarray(x), training=True)
    out = np.asarray(out, np.float32)
    # normalized output: ~zero mean, ~unit std per channel
    np.testing.assert_allclose(out.mean(0), 0.0, atol=1e-2)
    np.testing.assert_allclose(out.std(0), 1.0, atol=0.05)


def test_scaled_ws_conv2d_standardization():
    """ScaledWSConv2D uses g*(W-mean)/(std*sqrt(fan_in)) — the conv of a
    constant input must be ~zero (kernel mean removed), and the layer
    must differ from plain Conv2D with the same raw weights."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 8, 8, 3)).astype(np.float32))
    ws = nn.ScaledWSConv2D(4, 3, use_bias=False)
    v = ws.init(jax.random.PRNGKey(0), x)
    ones = jnp.ones_like(x)
    y0, _ = ws.apply(v, ones)
    # interior positions see the full kernel -> exactly the (zero) mean
    assert float(jnp.abs(y0[:, 1:-1, 1:-1, :]).max()) < 1e-5
    plain = nn.Conv2D(4, 3, use_bias=False)
    yp, _ = plain.apply(v, x)  # same raw kernel param
    yw, _ = ws.apply(v, x)
    assert float(jnp.abs(yw - yp).max()) > 1e-4


def test_scaled_ws_conv2d_skip_init_gradient_flows():
    """skip_init folds a zero-init scalar into the kernel: output is 0
    at init, but dL/d(skip_gain) is nonzero (weight-space adjoint), so
    the branch can learn away from zero."""
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(2, 6, 6, 3)).astype(np.float32))
    conv = nn.ScaledWSConv2D(4, 3, use_bias=False, skip_init=True,
                             branch_scale=0.5)
    v = conv.init(jax.random.PRNGKey(0), x)
    y, _ = conv.apply(v, x)
    np.testing.assert_allclose(np.asarray(y), 0.0, atol=1e-7)

    def loss(params):
        out, _ = conv.apply({**v, "params": params}, x)
        return jnp.sum(out * jnp.arange(out.size).reshape(out.shape))

    g = jax.grad(loss)(v["params"])
    sg = g["skip_gain"]
    assert float(jnp.abs(sg)) > 0.0
    # kernel grad is zero at skip_gain=0 (branch output independent of W)
    assert float(jnp.abs(g["kernel"]).max()) == 0.0


def test_fused_bn_matches_reference_forward_and_grad():
    """ops/fused_bn.bn_train (custom VJP used by BatchNormalization in
    channel-last training) must match the textbook f32 batch norm in
    value AND in x/gamma/beta gradients, including the mean/var output
    cotangent terms."""
    from analytics_zoo_tpu.ops import fused_bn

    rng = np.random.default_rng(2)
    x = jnp.asarray((3.0 + 1.5 * rng.normal(size=(4, 5, 5, 6)))
                    .astype(np.float32))
    g = jnp.asarray(rng.normal(size=(6,)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(6,)).astype(np.float32))

    def ref(x, g, b, eps=1e-3):
        m = x.mean((0, 1, 2))
        v = x.var((0, 1, 2))
        return (x - m) * jax.lax.rsqrt(v + eps) * g + b, m, v

    y, m, v = fused_bn.bn_train(x, g, b, 1e-3)
    yr, mr, vr = ref(x, g, b)
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr), atol=2e-5)
    np.testing.assert_allclose(np.asarray(m), np.asarray(mr), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(v), np.asarray(vr), rtol=1e-5)

    def mk_loss(fn):
        def loss(x, g, b):
            y, m, v = fn(x, g, b, 1e-3) if fn is fused_bn.bn_train \
                else fn(x, g, b)
            return (jnp.sum(jnp.sin(y)) + jnp.sum(m * 1.3)
                    + jnp.sum(v * 0.7))
        return loss

    gf = jax.grad(mk_loss(fused_bn.bn_train), argnums=(0, 1, 2))(x, g, b)
    gr = jax.grad(mk_loss(ref), argnums=(0, 1, 2))(x, g, b)
    for a, r in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   atol=3e-4, rtol=1e-4)


def test_batchnorm_training_uses_fused_path_consistently():
    """BatchNormalization training through the fused VJP must produce
    the same outputs/statistics as before (channel-last) and still work
    on the inline path (channel-first)."""
    rng = np.random.default_rng(3)
    x = (2.0 + rng.normal(size=(16, 4, 4, 8))).astype(np.float32)
    bn = nn.BatchNormalization(momentum=0.9)
    v = bn.init(jax.random.PRNGKey(0), jnp.asarray(x), training=True)
    out, state = bn.apply(v, jnp.asarray(x), training=True)
    out = np.asarray(out)
    np.testing.assert_allclose(out.mean((0, 1, 2)), 0.0, atol=1e-3)
    np.testing.assert_allclose(out.std((0, 1, 2)), 1.0, atol=0.05)
    # running stats updated toward batch stats
    st = state
    np.testing.assert_allclose(np.asarray(st["mean"]),
                               0.1 * x.mean((0, 1, 2)), rtol=1e-3)
    # channel-first falls back to the inline path and still normalizes
    bn1 = nn.BatchNormalization(axis=1)
    xc = np.transpose(x, (0, 3, 1, 2))
    v1 = bn1.init(jax.random.PRNGKey(0), jnp.asarray(xc), training=True)
    o1, _ = bn1.apply(v1, jnp.asarray(xc), training=True)
    np.testing.assert_allclose(np.asarray(o1).mean((0, 2, 3)), 0.0,
                               atol=1e-3)


def test_transformer_remat_attention_exact():
    """remat_attention=True must be numerically identical to the plain
    path in forward AND gradients — it only changes what is saved for
    the backward pass."""
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(size=(2, 12, 32)).astype(np.float32))
    base = nn.TransformerLayer(4)
    remat = nn.TransformerLayer(4, remat_attention=True)
    v = base.init(KEY, x)
    yb, _ = base.apply(v, x)
    yr, _ = remat.apply(v, x)  # same params: same layer structure
    np.testing.assert_allclose(np.asarray(yb), np.asarray(yr), atol=1e-6)

    def loss(params, layer):
        out, _ = layer.apply({"params": params}, x)
        return jnp.sum(jnp.sin(out))

    gb = jax.grad(loss)(v["params"], base)
    gr = jax.grad(loss)(v["params"], remat)
    for (pb, lb), (pr, lr) in zip(
            jax.tree_util.tree_leaves_with_path(gb),
            jax.tree_util.tree_leaves_with_path(gr)):
        assert pb == pr
        np.testing.assert_allclose(np.asarray(lb), np.asarray(lr),
                                   atol=1e-5)


def test_mha_remat_conflicts_with_kernel_paths():
    with pytest.raises(ValueError, match="remat"):
        nn.MultiHeadAttention(num_heads=2, use_flash=True, remat=True)


def test_mha_use_flash_auto_crossover(monkeypatch):
    """use_flash='auto' runs the dense path (+remat) below the measured
    crossover and the flash kernel at/above it — same math either way."""
    from analytics_zoo_tpu.nn import attention as attn_mod

    monkeypatch.setattr(attn_mod, "FLASH_AUTO_MIN_SEQ", 8)
    rng = np.random.default_rng(5)
    short = jnp.asarray(rng.normal(size=(2, 4, 16)).astype(np.float32))
    longx = jnp.asarray(rng.normal(size=(2, 8, 16)).astype(np.float32))
    auto = nn.MultiHeadAttention(num_heads=2, use_flash="auto", remat=True)
    dense = nn.MultiHeadAttention(num_heads=2)
    flash = nn.MultiHeadAttention(num_heads=2, use_flash=True)
    v = auto.init(KEY, short)
    # below crossover: identical to the dense path
    ys, _ = auto.apply(v, short)
    yd, _ = dense.apply(v, short)
    np.testing.assert_allclose(np.asarray(ys), np.asarray(yd), atol=1e-6)
    # at/above crossover: identical to the flash path (and close to dense)
    yl, _ = auto.apply(v, longx)
    yf, _ = flash.apply(v, longx)
    np.testing.assert_allclose(np.asarray(yl), np.asarray(yf), atol=1e-6)
    yld, _ = dense.apply(v, longx)
    np.testing.assert_allclose(np.asarray(yl), np.asarray(yld), atol=1e-4)


def test_mha_use_flash_validates_values():
    with pytest.raises(ValueError, match="use_flash"):
        nn.MultiHeadAttention(num_heads=2, use_flash="Auto")
