"""Extended Keras-1.2 layer zoo tests (reference pattern: keras layer specs
zoo/src/test/.../keras/layers/*Spec.scala — shape + forward checks)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import analytics_zoo_tpu.nn as nn


def _run(layer, x, training=False, seed=0):
    variables = layer.init(jax.random.PRNGKey(seed), x, training=training)
    out, _ = layer.apply(variables, x, training=training,
                         rng=jax.random.PRNGKey(seed + 1))
    return np.asarray(out)


@pytest.mark.parametrize("layer,shape,expect", [
    (nn.Conv3D(4, 3), (2, 5, 6, 7, 3), (2, 5, 6, 7, 4)),
    (nn.Conv3D(4, 2, strides=2, padding="valid"), (2, 4, 6, 8, 3),
     (2, 2, 3, 4, 4)),
    (nn.Conv2DTranspose(5, 3, strides=2), (2, 7, 7, 3), (2, 14, 14, 5)),
    (nn.DepthwiseConv2D(3, depth_multiplier=2), (2, 8, 8, 3), (2, 8, 8, 6)),
    (nn.SeparableConv2D(10, 3), (2, 8, 8, 4), (2, 8, 8, 10)),
    (nn.LocallyConnected1D(6, 3), (2, 10, 4), (2, 8, 6)),
    (nn.MaxPooling1D(2), (2, 10, 3), (2, 5, 3)),
    (nn.AveragePooling1D(2), (2, 10, 3), (2, 5, 3)),
    (nn.MaxPooling3D(2), (2, 4, 6, 8, 3), (2, 2, 3, 4, 3)),
    (nn.AveragePooling3D(2), (2, 4, 6, 8, 3), (2, 2, 3, 4, 3)),
    (nn.GlobalAveragePooling3D(), (2, 4, 5, 6, 3), (2, 3)),
    (nn.GlobalMaxPooling3D(), (2, 4, 5, 6, 3), (2, 3)),
    (nn.UpSampling1D(3), (2, 4, 5), (2, 12, 5)),
    (nn.UpSampling2D(2), (2, 3, 4, 5), (2, 6, 8, 5)),
    (nn.UpSampling3D(2), (2, 2, 3, 4, 5), (2, 4, 6, 8, 5)),
    (nn.ZeroPadding1D(2), (2, 5, 3), (2, 9, 3)),
    (nn.ZeroPadding3D(1), (2, 3, 4, 5, 2), (2, 5, 6, 7, 2)),
    (nn.Cropping1D(1), (2, 6, 3), (2, 4, 3)),
    (nn.Cropping2D(((1, 2), (0, 1))), (2, 8, 8, 3), (2, 5, 7, 3)),
    (nn.RepeatVector(4), (2, 7), (2, 4, 7)),
    (nn.Permute((2, 1)), (2, 3, 5), (2, 5, 3)),
    (nn.LeakyReLU(0.1), (2, 5), (2, 5)),
    (nn.ELU(), (2, 5), (2, 5)),
    (nn.ThresholdedReLU(0.5), (2, 5), (2, 5)),
    (nn.PReLU(), (2, 5), (2, 5)),
    (nn.Highway(), (3, 8), (3, 8)),
    (nn.MaxoutDense(6, nb_feature=3), (4, 10), (4, 6)),
])
def test_layer_output_shapes(layer, shape, expect):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=shape), jnp.float32)
    assert _run(layer, x).shape == expect


def test_upsampling_values():
    x = jnp.arange(4, dtype=jnp.float32).reshape(1, 2, 2, 1)
    out = _run(nn.UpSampling2D(2), x)
    np.testing.assert_array_equal(out[0, :, :, 0],
                                  [[0, 0, 1, 1], [0, 0, 1, 1],
                                   [2, 2, 3, 3], [2, 2, 3, 3]])


def test_depthwise_matches_grouped_dense_math():
    # depthwise with multiplier 1 == per-channel independent conv
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(1, 6, 6, 2)), jnp.float32)
    layer = nn.DepthwiseConv2D(3, use_bias=False, padding="valid")
    variables = layer.init(jax.random.PRNGKey(0), x)
    out, _ = layer.apply(variables, x)
    w = variables["params"]["kernel"]  # [3, 3, 1, 2]
    for c in range(2):
        ref = jax.lax.conv_general_dilated(
            x[..., c:c + 1], w[..., c:c + 1], (1, 1), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        np.testing.assert_allclose(np.asarray(out[..., c]),
                                   np.asarray(ref[..., 0]), atol=1e-5)


def test_merge_layers():
    a = jnp.asarray([[1.0, 2.0]])
    b = jnp.asarray([[3.0, 0.0]])
    assert np.allclose(_run(nn.Average(), [a, b]), [[2.0, 1.0]])
    assert np.allclose(_run(nn.Maximum(), [a, b]), [[3.0, 2.0]])
    assert np.allclose(_run(nn.Minimum(), [a, b]), [[1.0, 0.0]])
    assert np.allclose(_run(nn.Subtract(), [a, b]), [[-2.0, 2.0]])
    assert np.allclose(_run(nn.Dot(), [a, b]), [3.0])


def test_dot_distinct_axes_batch_dot():
    # keras batch_dot semantics: contract a axis 2 with b axis 1
    rng = np.random.default_rng(7)
    a = jnp.asarray(rng.normal(size=(2, 3, 4)), jnp.float32)
    b = jnp.asarray(rng.normal(size=(2, 4, 5)), jnp.float32)
    out = _run(nn.Dot(axes=(2, 1)), [a, b])
    assert out.shape == (2, 3, 5)
    np.testing.assert_allclose(out, np.einsum("bik,bkj->bij", a, b),
                               rtol=1e-5)


def test_dot_batch_axis_rejected():
    a = jnp.ones((2, 3))
    with pytest.raises(ValueError, match="batch dim"):
        _run(nn.Dot(axes=(0, 1)), [a, a])


def test_masking_zeroes_masked_steps():
    x = jnp.asarray([[[1.0, 2.0], [0.0, 0.0], [3.0, 0.0]]])
    out = _run(nn.Masking(0.0), x)
    np.testing.assert_array_equal(out[0, 1], [0.0, 0.0])
    np.testing.assert_array_equal(out[0, 2], [3.0, 0.0])


def test_stochastic_layers_train_vs_eval():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(4, 6, 8)), jnp.float32) + 5.0
    for layer in (nn.SpatialDropout1D(0.5), nn.GaussianNoise(1.0),
                  nn.GaussianDropout(0.5)):
        # eval: identity
        np.testing.assert_array_equal(_run(layer, x, training=False), x)
        # train: changes values
        assert not np.allclose(_run(layer, x, training=True), x)


def test_spatial_dropout_drops_whole_channels():
    x = jnp.ones((2, 16, 8), jnp.float32)
    out = _run(nn.SpatialDropout1D(0.5), x, training=True)
    # each (batch, channel) is either all-zero or all-scaled across time
    for bi in range(2):
        for c in range(8):
            col = out[bi, :, c]
            assert np.all(col == 0.0) or np.all(col == col[0])


def test_highway_carry_behavior():
    # with gate bias -1 the layer starts mostly-carry: output close to input
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(4, 16)), jnp.float32)
    out = _run(nn.Highway(), x)
    assert np.abs(out - np.asarray(x)).mean() < 1.0


def test_prelu_gradient_flows():
    x = jnp.asarray([[-2.0, 3.0]])
    layer = nn.PReLU()
    variables = layer.init(jax.random.PRNGKey(0), x)

    def loss(params):
        out, _ = layer.apply({"params": params}, x)
        return jnp.sum(out)

    g = jax.grad(loss)(variables["params"])
    assert np.asarray(g["alpha"])[0] != 0.0  # negative input drives alpha


def test_locally_connected_positions_independent():
    # different positions use different kernels: permuting time changes out
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(size=(1, 6, 3)), jnp.float32)
    layer = nn.LocallyConnected1D(2, 3, use_bias=False)
    variables = layer.init(jax.random.PRNGKey(0), x)
    out1, _ = layer.apply(variables, x)
    out2, _ = layer.apply(variables, x[:, ::-1])
    assert not np.allclose(np.asarray(out1)[:, ::-1], np.asarray(out2),
                           atol=1e-4)

def test_remat_matches_plain_forward_and_grad():
    import analytics_zoo_tpu.nn as nn2
    rng = np.random.default_rng(9)
    x = jnp.asarray(rng.normal(size=(4, 8)), jnp.float32)
    inner = nn2.Dense(8, activation="tanh", name="d")
    remat = nn2.Remat(inner)
    variables = remat.init(jax.random.PRNGKey(0), x)
    # forward matches under the same variables (loose tolerance: remat
    # changes the XLA fusion boundaries, so CPU results drift by a few ULP
    # even though the math is identical)
    out_r, _ = remat.apply(variables, x)
    out_p, _ = inner.apply({"params": variables["params"]["d"]}, x)
    np.testing.assert_allclose(np.asarray(out_r), np.asarray(out_p),
                               rtol=1e-4, atol=1e-6)

    def loss_plain(p):
        out, _ = inner.apply({"params": p["d"]}, x)
        return jnp.sum(out ** 2)

    def loss_remat(p):
        out, _ = remat.apply({"params": p}, x)
        return jnp.sum(out ** 2)

    g1 = jax.grad(loss_plain)(variables["params"])
    g2 = jax.grad(loss_remat)(variables["params"])
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a),
                                                np.asarray(b),
                                                rtol=1e-4, atol=1e-6),
        g1, g2)


def test_bert_remat_trains():
    from analytics_zoo_tpu.models import BERT
    from analytics_zoo_tpu.orca.learn import Estimator
    import analytics_zoo_tpu.nn as nn2

    class Clf(nn2.Module):
        def __init__(self):
            super().__init__()
            self.bert = BERT(vocab_size=40, hidden_size=32, n_layers=2,
                             n_heads=2, max_position=16, remat=True)

        def forward(self, scope, ids):
            h = scope.child(self.bert, ids, name="bert")
            return scope.child(nn2.Dense(2), h[:, 0], name="head")

    rng = np.random.default_rng(10)
    x = rng.integers(0, 40, (16, 12)).astype(np.int32)
    y = rng.integers(0, 2, 16).astype(np.int32)
    est = Estimator.from_keras(Clf(), loss="sparse_categorical_crossentropy")
    hist = est.fit((x, y), epochs=1, batch_size=8, verbose=False)
    assert np.isfinite(hist["loss"][0])


@pytest.mark.parametrize("layer,shape,expect", [
    (nn.Cropping3D(1), (2, 5, 6, 7, 3), (2, 3, 4, 5, 3)),
    (nn.SReLU(), (2, 5), (2, 5)),
    (nn.Select(dim=1, index=2), (2, 5, 3), (2, 3)),
    (nn.Narrow(dim=1, offset=1, length=3), (2, 6, 4), (2, 3, 4)),
    (nn.Squeeze(dim=2), (2, 5, 1, 3), (2, 5, 3)),
])
def test_tensor_op_layer_shapes(layer, shape, expect):
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.normal(size=shape), jnp.float32)
    assert _run(layer, x).shape == expect


def test_srelu_identity_between_thresholds():
    # init: t_left=0, a_left=0, t_right=1, a_right=1 → identity on [0, 1]
    x = jnp.asarray([[0.2, 0.8]])
    np.testing.assert_allclose(_run(nn.SReLU(), x), x, rtol=1e-6)
    # below t_left: clamps to t_left + 0*(x-t) = 0
    neg = jnp.asarray([[-3.0, -0.5]])
    np.testing.assert_allclose(_run(nn.SReLU(), neg), np.zeros((1, 2)),
                               atol=1e-6)


def test_squeeze_preserves_batch_of_one():
    x = jnp.zeros((1, 4, 1, 3))
    out = _run(nn.Squeeze(), x)
    assert out.shape == (1, 4, 3)  # axis 0 kept even at batch size 1


def test_narrow_length_to_end_and_select_oob():
    x = jnp.arange(12, dtype=jnp.float32).reshape(2, 6)
    out = _run(nn.Narrow(dim=1, offset=2, length=-1), x)
    np.testing.assert_array_equal(out, np.arange(12).reshape(2, 6)[:, 2:])
    with pytest.raises(ValueError, match="out of range"):
        _run(nn.Select(dim=1, index=99), x)
