"""Functional Model API tests (reference: keras Model graph topology —
Topology.scala Model + pyzoo keras models.py; two-tower/shared-weights
graphs were the reference's main model-building surface)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import analytics_zoo_tpu.nn as nn
from analytics_zoo_tpu.core import init_orca_context


@pytest.fixture(autouse=True)
def _ctx():
    init_orca_context("local")
    yield


def test_single_input_graph_matches_sequential():
    inp = nn.Input((8,))
    h = nn.Dense(16, activation="relu", name="d1")(inp)
    out = nn.Dense(2, name="d2")(h)
    model = nn.Model(inp, out)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(4, 8)),
                    jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x)
    y, _ = model.apply(variables, x)
    assert y.shape == (4, 2)
    assert set(variables["params"]) == {"d1", "d2"}


def test_multi_input_two_tower():
    user = nn.Input((6,))
    item = nn.Input((5,))
    u = nn.Dense(8, activation="relu")(user)
    v = nn.Dense(8, activation="relu")(item)
    merged = nn.Concatenate()([u, v])
    out = nn.Dense(1)(merged)
    model = nn.Model([user, item], out)
    rng = np.random.default_rng(1)
    xu = jnp.asarray(rng.normal(size=(4, 6)), jnp.float32)
    xi = jnp.asarray(rng.normal(size=(4, 5)), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), xu, xi)
    y, _ = model.apply(variables, xu, xi)
    assert y.shape == (4, 1)


def test_shared_layer_weights():
    # one Dense object applied to two inputs: ONE param subtree
    shared = nn.Dense(4, use_bias=False, name="shared")
    a = nn.Input((3,))
    b = nn.Input((3,))
    out = nn.Add()([shared(a), shared(b)])
    model = nn.Model([a, b], out)
    xa = jnp.ones((2, 3))
    xb = jnp.zeros((2, 3))
    variables = model.init(jax.random.PRNGKey(0), xa, xb)
    flat = jax.tree_util.tree_leaves(variables["params"])
    assert len(flat) == 1  # a single shared kernel
    y, _ = model.apply(variables, xa, xb)
    # Add(shared(ones), shared(zeros)) == shared(ones)
    w = variables["params"]["shared"]["kernel"]
    np.testing.assert_allclose(np.asarray(y), np.asarray(xa @ w),
                               rtol=1e-6)


def test_multi_output_graph():
    inp = nn.Input((4,))
    h = nn.Dense(8, activation="relu")(inp)
    out1 = nn.Dense(2, name="head_a")(h)
    out2 = nn.Dense(3, name="head_b")(h)
    model = nn.Model(inp, [out1, out2])
    x = jnp.ones((2, 4))
    variables = model.init(jax.random.PRNGKey(0), x)
    (ya, yb), _ = model.apply(variables, x)
    assert ya.shape == (2, 2) and yb.shape == (2, 3)


def test_symbolic_arithmetic_residual():
    inp = nn.Input((6,))
    h = nn.Dense(6, name="res")(inp)
    out = h + inp  # residual via operator sugar
    model = nn.Model(inp, out)
    x = jnp.ones((2, 6))
    variables = model.init(jax.random.PRNGKey(0), x)
    y, _ = model.apply(variables, x)
    _, _, taps = model.apply_with_taps(variables, x)
    np.testing.assert_allclose(np.asarray(y),
                               np.asarray(taps["res"] + x), rtol=1e-6)


def test_functional_model_trains_in_estimator():
    from analytics_zoo_tpu.orca.learn import Estimator
    inp = nn.Input((8,))
    h = nn.Dense(16, activation="relu")(inp)
    out = nn.Dense(2)(h)
    model = nn.Model(inp, out)
    rng = np.random.default_rng(2)
    x = rng.normal(size=(64, 8)).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.int32)
    est = Estimator.from_keras(model,
                               loss="sparse_categorical_crossentropy",
                               learning_rate=5e-2, metrics=["accuracy"])
    hist = est.fit((x, y), epochs=5, batch_size=16, verbose=False)
    assert hist["loss"][-1] < hist["loss"][0]
    assert est.evaluate((x, y), batch_size=16)["accuracy"] > 0.8


def test_reflected_operators():
    inp = nn.Input((4,))
    gate = nn.Dense(4, name="g")(inp)
    out = 1.0 - gate  # constant on the left (keras gate-inversion idiom)
    model = nn.Model(inp, out)
    x = jnp.zeros((2, 4))
    variables = model.init(jax.random.PRNGKey(0), x)
    y, _ = model.apply(variables, x)
    _, _, taps = model.apply_with_taps(variables, x)
    np.testing.assert_allclose(np.asarray(y), 1.0 - np.asarray(taps["g"]),
                               rtol=1e-6)
    # 2 * h and 1.0 + h build without TypeError too
    nn.Model(inp, 2 * gate)
    nn.Model(inp, 1.0 + gate)


def test_same_name_different_modules_raises():
    class Bad(nn.Module):
        def forward(self, scope, x):
            h = scope.child(nn.Dense(4), x, name="h")
            return scope.child(nn.Dense(8), h, name="h")  # name slip

    with pytest.raises(ValueError, match="different modules"):
        Bad().init(jax.random.PRNGKey(0), jnp.ones((2, 3)))


def test_shared_layer_taps_keep_both_applications():
    shared = nn.Dense(4, use_bias=False, name="shared")
    a = nn.Input((3,))
    b = nn.Input((3,))
    out = nn.Add()([shared(a), shared(b)])
    model = nn.Model([a, b], out)
    xa, xb = jnp.ones((2, 3)), jnp.zeros((2, 3))
    variables = model.init(jax.random.PRNGKey(0), xa, xb)
    _, _, taps = model.apply_with_taps(variables, xa, xb)
    keys = [k for k in taps if k.startswith("shared")]
    assert len(keys) == 2, sorted(taps)  # one tap per application
    vals = sorted(float(np.abs(np.asarray(taps[k])).sum()) for k in keys)
    assert vals[0] == 0.0 and vals[1] > 0.0  # zeros-tower and ones-tower


def test_unlisted_input_raises():
    a = nn.Input((3,))
    b = nn.Input((3,))
    out = nn.Add()([nn.Dense(2)(a), nn.Dense(2)(b)])
    with pytest.raises(ValueError, match="not in"):
        nn.Model(a, out)  # b is reachable but not declared
def test_child_seen_holds_reference_not_id():
    """Regression (round-2 advisor): the duplicate-name guard must keep the
    module OBJECT alive, not just id() — a GC'd module's address can be
    reused by a different module, silently defeating the guard."""
    import gc

    class TwoInline(nn.Module):
        def forward(self, scope, x):
            # first module constructed inline: without a kept reference it
            # would be collectible right after its child() call
            h = scope.child(nn.Dense(4), x, name="h")
            gc.collect()
            return scope.child(nn.Dense(8), h, name="h")  # different module

    with pytest.raises(ValueError, match="different modules"):
        TwoInline().init(jax.random.PRNGKey(0), jnp.ones((2, 3)))
