"""Failure-recovery tests (SURVEY.md §5.3): preemption checkpointing,
zoo-launch gang supervision, and training-loop self-healing.

The real contracts — SIGTERM mid-training → checkpoint lands → process
exits → a fresh process resumes; a crashed/hung gang worker → supervisor
kills and relaunches the gang → workers auto-resume — are exercised with
actual OS processes and signals, the cluster-in-a-box way the reference
tested failure paths.  The NaN self-healing policies run in-process with
the ``step.nan`` injection point."""

import os
import re
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "preemption_worker.py")


def _spawn(model_dir, *args):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return subprocess.Popen(
        [sys.executable, WORKER, str(model_dir), *args], env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)


def test_sigterm_checkpoints_and_resumes(tmp_path):
    model_dir = tmp_path / "ckpt"
    # phase 1: train until SIGTERM
    proc = _spawn(model_dir)
    # wait for the train loop to actually start before signalling
    line = ""
    deadline = time.time() + 180
    while "TRAINING_STARTED" not in line:
        assert time.time() < deadline, "worker never started training"
        line = proc.stdout.readline()
    time.sleep(1.0)  # let a few steps run
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=120)
    assert proc.returncode == 143, out[-3000:]
    m = re.search(r"PREEMPTED step=(\d+)", out)
    assert m, out[-3000:]
    preempted_step = int(m.group(1))
    assert preempted_step > 0
    assert (model_dir / "treedef.json").exists()

    # phase 2: fresh process auto-resumes past the preempted step.
    # ``epochs`` is a TOTAL target, so derive it from the checkpoint's
    # saved epoch — a fixed "1" trains ZERO further epochs whenever the
    # fast phase-1 run already got past epoch 1 before the signal landed
    from analytics_zoo_tpu.core import checkpoint as ckpt_io
    saved_epoch = ckpt_io.load_extra(str(model_dir)).get("epoch", 0)
    proc2 = _spawn(model_dir, str(saved_epoch + 2))
    out2, _ = proc2.communicate(timeout=180)
    assert proc2.returncode == 0, out2[-3000:]
    m2 = re.search(r"FINISHED step=(\d+)", out2)
    assert m2, out2[-3000:]
    assert int(m2.group(1)) > preempted_step


def test_guard_consensus_single_process():
    from analytics_zoo_tpu.core import PreemptionGuard
    g = PreemptionGuard(sync_every=4)
    g.active = True  # inside fit(): flag-and-continue mode
    # no signal: never fires
    assert not g.should_checkpoint(4)
    g._on_signal(signal.SIGTERM, None)
    # fires only at sync points
    assert not g.should_checkpoint(5)
    assert g.should_checkpoint(8)


def test_guard_inactive_signal_chains_to_default():
    # outside fit() a signal must NOT be swallowed: the guard re-raises
    # via the previous handler (KeyboardInterrupt for SIGINT)
    import pytest
    from analytics_zoo_tpu.core import PreemptionGuard
    g = PreemptionGuard(sync_every=2).install()
    try:
        assert g._installed
        with pytest.raises(KeyboardInterrupt):
            g._on_signal(signal.SIGINT, None)
        assert not g.flagged
    finally:
        g.uninstall()


def test_preempted_reports_durable_step_exactly():
    """Step 0 is a real durable recovery point (must not be replaced by
    a falsy-or fallback), and a grace-window miss is flagged via
    ``durable=False`` so callers don't assume the step is on disk."""
    from analytics_zoo_tpu.core.failover import Preempted
    landed = Preempted(0, "/ckpt")
    assert landed.step == 0 and landed.durable
    missed = Preempted(7, "/ckpt", durable=False)
    assert missed.step == 7 and not missed.durable
    assert "NOT durable" in str(missed)


def test_preemption_requires_model_dir():
    import pytest
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.orca.learn import Estimator
    with pytest.raises(ValueError, match="model_dir"):
        Estimator.from_keras(nn.Sequential([nn.Dense(1)]), loss="mse",
                             preemption_checkpoint=True)

def test_guard_inactive_signal_chains_to_callable_prev():
    """A signal while active=False must re-raise through the PREVIOUS
    handler when that handler is a plain callable (e.g. an application's
    own SIGTERM hook), and must NOT set the checkpoint flag."""
    from analytics_zoo_tpu.core import PreemptionGuard
    calls = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: calls.append(s))
    g = PreemptionGuard(sync_every=2).install()
    try:
        assert g.active is False
        g._on_signal(signal.SIGTERM, None)
        assert calls == [signal.SIGTERM]  # chained, not swallowed
        assert not g.flagged
        # a second delivery chains again (the guard stays installed)
        g._on_signal(signal.SIGTERM, None)
        assert calls == [signal.SIGTERM] * 2
    finally:
        g.uninstall()
        signal.signal(signal.SIGTERM, prev)


def test_guard_inactive_signal_sig_dfl_reraises():
    """When the previous handler was SIG_DFL the guard must restore
    SIG_DFL and re-raise the signal so the default action runs (for
    SIGTERM: process death).  Verified with the signal plumbing mocked —
    letting the default action run would kill pytest."""
    from unittest import mock
    from analytics_zoo_tpu.core import PreemptionGuard
    from analytics_zoo_tpu.core import failover
    g = PreemptionGuard(sync_every=2)
    g._prev_handlers[signal.SIGTERM] = signal.SIG_DFL
    g._installed = True
    try:
        with mock.patch.object(failover.signal, "signal") as m_sig, \
                mock.patch.object(failover.signal,
                                  "raise_signal") as m_raise:
            g._on_signal(signal.SIGTERM, None)
        m_sig.assert_called_once_with(signal.SIGTERM, signal.SIG_DFL)
        m_raise.assert_called_once_with(signal.SIGTERM)
        assert not g.flagged
    finally:
        g._installed = False
        g._prev_handlers.clear()


def test_uninstall_restores_handlers_exactly_once():
    """uninstall() puts the pre-install handlers back and becomes a no-op:
    a second uninstall must NOT clobber handlers someone registered in
    between (double-restore would undo the newer registration)."""
    from analytics_zoo_tpu.core import PreemptionGuard
    h0 = lambda s, f: None  # noqa: E731
    prev = signal.signal(signal.SIGTERM, h0)
    try:
        g = PreemptionGuard(sync_every=2).install()
        assert signal.getsignal(signal.SIGTERM) == g._on_signal
        g.uninstall()
        assert signal.getsignal(signal.SIGTERM) is h0  # restored
        h1 = lambda s, f: None  # noqa: E731
        signal.signal(signal.SIGTERM, h1)
        g.uninstall()  # second call: must not touch handlers
        assert signal.getsignal(signal.SIGTERM) is h1
        # and a fresh install/uninstall cycle still works
        g.install()
        assert signal.getsignal(signal.SIGTERM) == g._on_signal
        g.uninstall()
        assert signal.getsignal(signal.SIGTERM) is h1
    finally:
        signal.signal(signal.SIGTERM, prev)


def test_signal_handler_is_lock_free():
    """Regression (round-2 advisor): the handler body must take NO lock —
    not the guard's own (removed) lock, and not the logging module's (via
    logger.warning) — because a signal arriving while the main thread holds
    such a lock deadlocks the process exactly during preemption.  Locks are
    reentrant on the same thread, so holding them here proves nothing;
    instead assert the handler never *calls* any locking primitive: logging
    is stubbed to raise, and flag delivery is still observed."""
    import logging
    from unittest import mock
    from analytics_zoo_tpu.core import PreemptionGuard
    from analytics_zoo_tpu.core import failover
    g = PreemptionGuard(sync_every=1)
    g.active = True
    with mock.patch.object(failover.logger, "warning",
                           side_effect=AssertionError(
                               "logging inside the signal handler")), \
         mock.patch.object(logging.Handler, "acquire",
                           side_effect=AssertionError(
                               "lock acquire inside the signal handler")):
        g._on_signal(signal.SIGTERM, None)
        assert g._flag  # raw flag read: .flagged may log (that's fine)
    # outside the handler the deferred warning drains via normal reads
    assert g.flagged
    assert g.should_checkpoint(1)


# -- gang supervision (core/launcher.py) -------------------------------------
# Fast supervisor-logic tests use tiny non-jax scripts; the end-to-end gang
# test (the acceptance contract) spawns real training workers.

def _script(tmp_path, name, body):
    p = tmp_path / name
    p.write_text(body)
    return str(p)


@pytest.mark.gang
def test_supervisor_restarts_crashed_gang(tmp_path):
    from analytics_zoo_tpu.core.launcher import launch
    s = _script(tmp_path, "s.py",
                "import os, sys\n"
                "sys.exit(1 if os.environ['ZOO_RESTART_COUNT'] == '0' "
                "else 0)\n")
    events = []
    rc = launch(s, [], nprocs=2, max_restarts=1, backoff=0.05, grace=1.0,
                on_event=lambda k, i: events.append((k, i)))
    assert rc == 0
    kinds = [k for k, _ in events]
    assert kinds == ["crash", "restart", "ok"]
    assert events[0][1]["rc"] == 1


@pytest.mark.gang
def test_supervisor_detects_dead_worker_promptly(tmp_path):
    """A dead worker must be detected while its siblings still run — the
    pre-supervisor sequential wait() could block up to nprocs * timeout."""
    from analytics_zoo_tpu.core.launcher import launch
    s = _script(tmp_path, "s.py",
                "import os, sys, time\n"
                "sys.exit(2) if os.environ['ZOO_PROCESS_ID'] == '0' "
                "else time.sleep(60)\n")
    t0 = time.monotonic()
    rc = launch(s, [], nprocs=3, max_restarts=0, grace=0.5)
    assert rc == 2
    assert time.monotonic() - t0 < 20  # nowhere near the 60 s sleeper


@pytest.mark.gang
def test_supervisor_crash_loop_aborts_with_diagnosis(tmp_path):
    from analytics_zoo_tpu.core.launcher import EXIT_CRASH_LOOP, launch
    s = _script(tmp_path, "s.py",
                "import os, sys, time\n"
                "sys.exit(3) if os.environ['ZOO_PROCESS_ID'] == '1' "
                "else time.sleep(60)\n")
    events = []
    rc = launch(s, [], nprocs=2, max_restarts=10, backoff=0.05, grace=0.5,
                crash_loop_threshold=2,
                on_event=lambda k, i: events.append((k, i)))
    assert rc == EXIT_CRASH_LOOP
    assert events[-1][0] == "crash_loop"
    assert events[-1][1]["rank"] == 1
    # budget was NOT exhausted: the loop was diagnosed after 2 attempts
    assert sum(1 for k, _ in events if k == "crash") == 2


@pytest.mark.gang
def test_supervisor_restart_budget_exhausted_returns_rc(tmp_path):
    from analytics_zoo_tpu.core.launcher import launch
    s = _script(tmp_path, "s.py", "import sys\nsys.exit(7)\n")
    rc = launch(s, [], nprocs=1, max_restarts=1, backoff=0.05, grace=0.5,
                crash_loop_threshold=5)
    assert rc == 7


@pytest.mark.gang
def test_supervisor_kills_and_restarts_on_heartbeat_loss(tmp_path):
    """A worker that never beats (hung before/at startup) is killed and
    the gang restarted — hung workers must not stall the job forever."""
    from analytics_zoo_tpu.core.launcher import launch
    s = _script(tmp_path, "s.py",
                "import os, sys, time\n"
                "time.sleep(60) if os.environ['ZOO_RESTART_COUNT'] == '0' "
                "else sys.exit(0)\n")
    events = []
    t0 = time.monotonic()
    rc = launch(s, [], nprocs=2, max_restarts=1, backoff=0.05, grace=0.5,
                heartbeat_timeout=1.0,
                on_event=lambda k, i: events.append((k, i)))
    assert rc == 0
    assert [k for k, _ in events] == ["hang", "restart", "ok"]
    assert time.monotonic() - t0 < 30


@pytest.mark.gang
def test_supervisor_slow_but_beating_worker_is_left_alone(tmp_path):
    """Hung vs slow: a worker that keeps touching its heartbeat file is
    slow, not dead — no restart even while it takes >> heartbeat_timeout."""
    from analytics_zoo_tpu.core.launcher import launch
    s = _script(tmp_path, "s.py",
                "import os, time\n"
                "hb = os.environ['ZOO_HEARTBEAT_FILE']\n"
                "for _ in range(8):\n"
                "    time.sleep(0.25)\n"
                "    os.utime(hb, None)\n")
    events = []
    rc = launch(s, [], nprocs=2, max_restarts=1, backoff=0.05, grace=0.5,
                heartbeat_timeout=1.0,
                on_event=lambda k, i: events.append((k, i)))
    assert rc == 0
    assert [k for k, _ in events] == ["ok"]  # ran ~2 s, never restarted


@pytest.mark.gang
def test_gang_crash_restart_resumes_to_completion(tmp_path):
    """THE acceptance contract: a 3-worker zoo-launch gang with
    ``worker.crash`` armed on worker 1 (via the injection point inside the
    train loop) finishes training with the correct final step — the
    supervisor terminates the gang on the crash, relaunches it, and every
    worker auto-resumes from its epoch checkpoint."""
    from analytics_zoo_tpu.core.launcher import launch
    env = {"ZOO_GANG_MODE": "1", "ZOO_TEST_FAULT_WORKER": "1",
           "ZOO_TEST_CRASH_AFTER": "10",  # crash at step 11, mid-epoch 2
           "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                            ""),
           "JAX_PLATFORMS": "cpu"}
    old = {k: os.environ.get(k)
           for k in list(env) + ["PALLAS_AXON_POOL_IPS"]}
    os.environ.update(env)
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    events = []
    try:
        rc = launch(WORKER, [str(tmp_path), "3"], nprocs=3,
                    platform="cpu", max_restarts=2, backoff=0.1,
                    grace=15.0,
                    on_event=lambda k, i: events.append((k, i)))
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else \
                os.environ.__setitem__(k, v)
    assert rc == 0, events
    kinds = [k for k, _ in events]
    assert kinds == ["crash", "restart", "ok"], events
    assert events[0][1]["rank"] == 1  # the armed worker was the culprit
    # every worker reached the exact final step: 3 epochs x 8 steps
    for pid in range(3):
        done = tmp_path / f"done_w{pid}"
        assert done.exists(), f"worker {pid} never finished"
        assert int(done.read_text()) == 24


# -- training-loop self-healing (nan_policy) ---------------------------------

def _small_fit_setup():
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.core import init_orca_context
    from analytics_zoo_tpu.orca.learn import Estimator
    init_orca_context("local")
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 4)).astype(np.float32)
    y = rng.normal(size=(64, 1)).astype(np.float32)

    def mkest(**kw):
        model = nn.Sequential([nn.Dense(8, activation="relu"),
                               nn.Dense(1)])
        return Estimator.from_keras(model, loss="mse", learning_rate=1e-3,
                                    **kw)

    return mkest, x, y


@pytest.mark.faults
def test_nan_policy_warn_counts_and_continues():
    from analytics_zoo_tpu.core import faults
    mkest, x, y = _small_fit_setup()
    est = mkest(nan_policy="warn")
    with faults.get_registry().armed("step.nan", times=1, after=1):
        hist = est.fit((x, y), epochs=1, batch_size=32, verbose=False)
    assert est.bad_steps == 1
    assert hist["bad_steps"] == [1]
    assert faults.get_registry().fired("step.nan") == 1


@pytest.mark.faults
def test_nan_policy_skip_step_keeps_params_finite():
    import jax
    from analytics_zoo_tpu.core import faults
    mkest, x, y = _small_fit_setup()
    est = mkest(nan_policy="skip_step")
    with faults.get_registry().armed("step.nan", times=1, after=1):
        hist = est.fit((x, y), epochs=1, batch_size=32, verbose=False)
    # the poisoned step was skipped on-device: params stayed finite and
    # the epoch loss (nanmean over the good steps) is finite
    assert est.bad_steps == 1
    assert hist["bad_steps"] == [1]
    assert np.isfinite(hist["loss"][0])
    leaves = jax.tree_util.tree_leaves(est.get_model()["params"])
    assert all(np.isfinite(np.asarray(l)).all() for l in leaves)


@pytest.mark.faults
def test_nan_policy_raise_raises():
    from analytics_zoo_tpu.core import faults
    from analytics_zoo_tpu.orca.learn import NonFiniteLossError
    mkest, x, y = _small_fit_setup()
    est = mkest(nan_policy="raise")
    with faults.get_registry().armed("step.nan", times=1):
        with pytest.raises(NonFiniteLossError, match="non-finite loss"):
            est.fit((x, y), epochs=1, batch_size=32, verbose=False)
    assert est.bad_steps == 1


@pytest.mark.faults
def test_nan_policy_rollback_recovers_pre_nan_checkpoint(tmp_path):
    """Acceptance contract: an armed ``step.nan`` under
    ``policy="rollback"`` recovers to the pre-NaN checkpoint — the final
    history equals a clean run's (same seed, same data, NaN step never
    applied) and training completes every epoch."""
    from analytics_zoo_tpu.core import faults, stop_orca_context
    mkest, x, y = _small_fit_setup()
    clean = mkest().fit((x, y), epochs=2, batch_size=32, verbose=False)

    stop_orca_context()
    mkest, x, y = _small_fit_setup()
    est = mkest(nan_policy="rollback", model_dir=str(tmp_path / "ckpt"))
    # 2 steps/epoch; checkpoint at each epoch end; NaN on step 3 (epoch 2)
    with faults.get_registry().armed("step.nan", times=1, after=2):
        hist = est.fit((x, y), epochs=2, batch_size=32,
                       checkpoint_trigger="every_epoch", verbose=False)
    assert est._rollbacks == 1
    assert est.bad_steps == 1
    assert est._py_step == 4  # rewound to step 2, re-ran epoch 2 cleanly
    np.testing.assert_allclose(hist["loss"], clean["loss"], rtol=1e-6)


@pytest.mark.faults
def test_nan_policy_rollback_without_checkpoint_raises():
    from analytics_zoo_tpu.core import faults
    from analytics_zoo_tpu.orca.learn import NonFiniteLossError
    mkest, x, y = _small_fit_setup()
    est = mkest(nan_policy="rollback")  # no model_dir -> nothing to restore
    with faults.get_registry().armed("step.nan", times=1):
        with pytest.raises(NonFiniteLossError, match="no checkpoint"):
            est.fit((x, y), epochs=1, batch_size=32, verbose=False)


def test_nan_policy_validated():
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.orca.learn import Estimator
    with pytest.raises(ValueError, match="nan_policy"):
        Estimator.from_keras(nn.Sequential([nn.Dense(1)]), loss="mse",
                             nan_policy="explode")


# -- worker heartbeat (core/context.py) --------------------------------------

def test_fit_beats_heartbeat_file(tmp_path):
    """The training loop reports liveness: with a heartbeat file
    configured, fit() touches it on progress (the supervisor's hung-vs-
    slow signal)."""
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.core import ZooConfig, init_orca_context
    from analytics_zoo_tpu.orca.learn import Estimator
    hb = tmp_path / "hb"
    init_orca_context("local", config=ZooConfig(
        heartbeat_file=str(hb), heartbeat_interval=0.01))
    assert hb.exists()  # first beat lands at init ("import finished")
    mtime0 = hb.stat().st_mtime
    time.sleep(0.05)
    rng = np.random.default_rng(0)
    est = Estimator.from_keras(
        nn.Sequential([nn.Dense(1)]), loss="mse", learning_rate=1e-3)
    est.fit((rng.normal(size=(64, 4)).astype(np.float32),
             rng.normal(size=(64, 1)).astype(np.float32)),
            epochs=1, batch_size=32, verbose=False)
    assert hb.stat().st_mtime > mtime0


def test_heartbeat_env_contract(tmp_path, monkeypatch):
    """init_orca_context picks the heartbeat file up from the env vars the
    zoo-launch supervisor sets."""
    from analytics_zoo_tpu.core import OrcaContext, init_orca_context
    hb = tmp_path / "hb_env"
    monkeypatch.setenv("ZOO_HEARTBEAT_FILE", str(hb))
    monkeypatch.setenv("ZOO_HEARTBEAT_INTERVAL", "0.25")
    init_orca_context("local")
    assert hb.exists()
    assert OrcaContext.config.heartbeat_interval == 0.25


@pytest.mark.faults
def test_worker_hang_fault_wedges_a_step():
    """The ``worker.hang`` seam sits in the train loop: an armed delay
    stalls exactly one step (and with it the heartbeat) — the injection
    the supervisor-side heartbeat tests build on."""
    from analytics_zoo_tpu.core import faults
    mkest, x, y = _small_fit_setup()
    est = mkest()
    t0 = time.monotonic()
    with faults.get_registry().armed("worker.hang", times=1, delay=0.3):
        est.fit((x, y), epochs=1, batch_size=32, verbose=False)
    assert time.monotonic() - t0 >= 0.3
    assert faults.get_registry().fired("worker.hang") == 1


@pytest.mark.faults
def test_skip_step_bad_counter_survives_resume(tmp_path):
    """Resume semantics for the on-device bad-step counter: a fresh
    estimator loading a skip_step checkpoint syncs its host mirror, so
    post-resume epochs report only THEIR bad steps."""
    from analytics_zoo_tpu.core import faults
    mkest, x, y = _small_fit_setup()
    est = mkest(nan_policy="skip_step", model_dir=str(tmp_path / "ck"))
    with faults.get_registry().armed("step.nan", times=1, after=1):
        est.fit((x, y), epochs=1, batch_size=32,
                checkpoint_trigger="every_epoch", verbose=False)
    assert est.bad_steps == 1
    est2 = mkest(nan_policy="skip_step", model_dir=str(tmp_path / "ck"))
    est2.load()
    assert est2.bad_steps == 1  # host mirror synced from the checkpoint
    hist = est2.fit((x, y), epochs=2, batch_size=32, verbose=False)
    # the resumed epochs ran clean: per-epoch counts exclude the
    # checkpoint's historical bad step
    assert hist["bad_steps"] == [0, 0]
    assert est2.bad_steps == 1  # total still includes history
