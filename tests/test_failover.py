"""Preemption-safe training tests (SURVEY.md §5.3 failure recovery).

The real contract — SIGTERM mid-training → checkpoint lands → process
exits → a fresh process resumes from the step it left — is exercised with
actual OS signals on a subprocess, the cluster-in-a-box way the reference
tested failure paths."""

import os
import re
import signal
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "preemption_worker.py")


def _spawn(model_dir, *args):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return subprocess.Popen(
        [sys.executable, WORKER, str(model_dir), *args], env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)


def test_sigterm_checkpoints_and_resumes(tmp_path):
    model_dir = tmp_path / "ckpt"
    # phase 1: train until SIGTERM
    proc = _spawn(model_dir)
    # wait for the train loop to actually start before signalling
    line = ""
    deadline = time.time() + 180
    while "TRAINING_STARTED" not in line:
        assert time.time() < deadline, "worker never started training"
        line = proc.stdout.readline()
    time.sleep(1.0)  # let a few steps run
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=120)
    assert proc.returncode == 143, out[-3000:]
    m = re.search(r"PREEMPTED step=(\d+)", out)
    assert m, out[-3000:]
    preempted_step = int(m.group(1))
    assert preempted_step > 0
    assert (model_dir / "treedef.json").exists()

    # phase 2: fresh process auto-resumes past the preempted step.
    # ``epochs`` is a TOTAL target, so derive it from the checkpoint's
    # saved epoch — a fixed "1" trains ZERO further epochs whenever the
    # fast phase-1 run already got past epoch 1 before the signal landed
    from analytics_zoo_tpu.core import checkpoint as ckpt_io
    saved_epoch = ckpt_io.load_extra(str(model_dir)).get("epoch", 0)
    proc2 = _spawn(model_dir, str(saved_epoch + 2))
    out2, _ = proc2.communicate(timeout=180)
    assert proc2.returncode == 0, out2[-3000:]
    m2 = re.search(r"FINISHED step=(\d+)", out2)
    assert m2, out2[-3000:]
    assert int(m2.group(1)) > preempted_step


def test_guard_consensus_single_process():
    from analytics_zoo_tpu.core import PreemptionGuard
    g = PreemptionGuard(sync_every=4)
    g.active = True  # inside fit(): flag-and-continue mode
    # no signal: never fires
    assert not g.should_checkpoint(4)
    g._on_signal(signal.SIGTERM, None)
    # fires only at sync points
    assert not g.should_checkpoint(5)
    assert g.should_checkpoint(8)


def test_guard_inactive_signal_chains_to_default():
    # outside fit() a signal must NOT be swallowed: the guard re-raises
    # via the previous handler (KeyboardInterrupt for SIGINT)
    import pytest
    from analytics_zoo_tpu.core import PreemptionGuard
    g = PreemptionGuard(sync_every=2).install()
    try:
        assert g._installed
        with pytest.raises(KeyboardInterrupt):
            g._on_signal(signal.SIGINT, None)
        assert not g.flagged
    finally:
        g.uninstall()


def test_preemption_requires_model_dir():
    import pytest
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.orca.learn import Estimator
    with pytest.raises(ValueError, match="model_dir"):
        Estimator.from_keras(nn.Sequential([nn.Dense(1)]), loss="mse",
                             preemption_checkpoint=True)

def test_guard_inactive_signal_chains_to_callable_prev():
    """A signal while active=False must re-raise through the PREVIOUS
    handler when that handler is a plain callable (e.g. an application's
    own SIGTERM hook), and must NOT set the checkpoint flag."""
    from analytics_zoo_tpu.core import PreemptionGuard
    calls = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: calls.append(s))
    g = PreemptionGuard(sync_every=2).install()
    try:
        assert g.active is False
        g._on_signal(signal.SIGTERM, None)
        assert calls == [signal.SIGTERM]  # chained, not swallowed
        assert not g.flagged
        # a second delivery chains again (the guard stays installed)
        g._on_signal(signal.SIGTERM, None)
        assert calls == [signal.SIGTERM] * 2
    finally:
        g.uninstall()
        signal.signal(signal.SIGTERM, prev)


def test_guard_inactive_signal_sig_dfl_reraises():
    """When the previous handler was SIG_DFL the guard must restore
    SIG_DFL and re-raise the signal so the default action runs (for
    SIGTERM: process death).  Verified with the signal plumbing mocked —
    letting the default action run would kill pytest."""
    from unittest import mock
    from analytics_zoo_tpu.core import PreemptionGuard
    from analytics_zoo_tpu.core import failover
    g = PreemptionGuard(sync_every=2)
    g._prev_handlers[signal.SIGTERM] = signal.SIG_DFL
    g._installed = True
    try:
        with mock.patch.object(failover.signal, "signal") as m_sig, \
                mock.patch.object(failover.signal,
                                  "raise_signal") as m_raise:
            g._on_signal(signal.SIGTERM, None)
        m_sig.assert_called_once_with(signal.SIGTERM, signal.SIG_DFL)
        m_raise.assert_called_once_with(signal.SIGTERM)
        assert not g.flagged
    finally:
        g._installed = False
        g._prev_handlers.clear()


def test_uninstall_restores_handlers_exactly_once():
    """uninstall() puts the pre-install handlers back and becomes a no-op:
    a second uninstall must NOT clobber handlers someone registered in
    between (double-restore would undo the newer registration)."""
    from analytics_zoo_tpu.core import PreemptionGuard
    h0 = lambda s, f: None  # noqa: E731
    prev = signal.signal(signal.SIGTERM, h0)
    try:
        g = PreemptionGuard(sync_every=2).install()
        assert signal.getsignal(signal.SIGTERM) == g._on_signal
        g.uninstall()
        assert signal.getsignal(signal.SIGTERM) is h0  # restored
        h1 = lambda s, f: None  # noqa: E731
        signal.signal(signal.SIGTERM, h1)
        g.uninstall()  # second call: must not touch handlers
        assert signal.getsignal(signal.SIGTERM) is h1
        # and a fresh install/uninstall cycle still works
        g.install()
        assert signal.getsignal(signal.SIGTERM) == g._on_signal
        g.uninstall()
        assert signal.getsignal(signal.SIGTERM) is h1
    finally:
        signal.signal(signal.SIGTERM, prev)


def test_signal_handler_is_lock_free():
    """Regression (round-2 advisor): the handler body must take NO lock —
    not the guard's own (removed) lock, and not the logging module's (via
    logger.warning) — because a signal arriving while the main thread holds
    such a lock deadlocks the process exactly during preemption.  Locks are
    reentrant on the same thread, so holding them here proves nothing;
    instead assert the handler never *calls* any locking primitive: logging
    is stubbed to raise, and flag delivery is still observed."""
    import logging
    from unittest import mock
    from analytics_zoo_tpu.core import PreemptionGuard
    from analytics_zoo_tpu.core import failover
    g = PreemptionGuard(sync_every=1)
    g.active = True
    with mock.patch.object(failover.logger, "warning",
                           side_effect=AssertionError(
                               "logging inside the signal handler")), \
         mock.patch.object(logging.Handler, "acquire",
                           side_effect=AssertionError(
                               "lock acquire inside the signal handler")):
        g._on_signal(signal.SIGTERM, None)
        assert g._flag  # raw flag read: .flagged may log (that's fine)
    # outside the handler the deferred warning drains via normal reads
    assert g.flagged
    assert g.should_checkpoint(1)
