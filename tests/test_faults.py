"""Fault-injection harness unit tests (core/faults.py).

The registry itself must be boring and exact: disabled points are no-ops,
armed points fire deterministically (seeded), counts are bounded, and the
scoped helpers always disarm.  Every resilience test in the suite builds
on these guarantees.
"""

import threading
import time

import pytest

from analytics_zoo_tpu.core.faults import (FaultRegistry, KNOWN_POINTS,
                                           get_registry, register_point)

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean_global_registry():
    get_registry().reset()
    yield
    get_registry().reset()


def test_disarmed_point_is_noop():
    r = FaultRegistry()
    assert not r.fire("serving.conn_drop")
    r.raise_if("checkpoint.write_fail")  # must not raise
    assert r.hits("serving.conn_drop") == 1
    assert r.fired("serving.conn_drop") == 0


def test_enable_unknown_point_raises():
    r = FaultRegistry()
    with pytest.raises(ValueError, match="unknown injection point"):
        r.enable("serving.conn_dorp")  # typo must fail loudly


def test_register_point_extends_known_set():
    name = register_point("serving.test_only_point")
    try:
        r = FaultRegistry()
        r.enable(name, times=1)
        assert r.fire(name)
    finally:
        KNOWN_POINTS.discard(name)


def test_times_bounds_fires_then_disarms():
    r = FaultRegistry()
    r.enable("serving.queue_reject", times=3)
    fires = [r.fire("serving.queue_reject") for _ in range(10)]
    assert fires == [True] * 3 + [False] * 7
    assert not r.is_armed("serving.queue_reject")
    assert r.fired("serving.queue_reject") == 3
    assert r.hits("serving.queue_reject") == 10


def test_prob_is_seeded_and_deterministic():
    def run(seed):
        r = FaultRegistry()
        r.enable("feed.stall", prob=0.5, seed=seed)
        return [r.fire("feed.stall") for _ in range(64)]

    a, b = run(7), run(7)
    assert a == b  # same seed, same firing pattern
    assert any(a) and not all(a)  # actually probabilistic
    assert run(7) != run(8)  # and seed-dependent


def test_raise_if_uses_armed_exception_type():
    r = FaultRegistry()
    r.enable("checkpoint.write_fail", times=1, exc=OSError,
             message="disk on fire")
    with pytest.raises(OSError, match="disk on fire"):
        r.raise_if("checkpoint.write_fail")
    r.raise_if("checkpoint.write_fail")  # charge consumed: no-op now


def test_raise_if_default_exception_is_runtime_error():
    r = FaultRegistry()
    r.enable("checkpoint.write_fail", times=1)
    with pytest.raises(RuntimeError, match="injected fault"):
        r.raise_if("checkpoint.write_fail")


def test_delay_sleeps_on_fire_only():
    r = FaultRegistry()
    r.enable("serving.model_latency", times=1, delay=0.05)
    t0 = time.monotonic()
    assert r.fire("serving.model_latency")
    assert time.monotonic() - t0 >= 0.05
    t0 = time.monotonic()
    assert not r.fire("serving.model_latency")  # disarmed: no sleep
    assert time.monotonic() - t0 < 0.04


def test_armed_context_manager_disarms_on_exit():
    r = FaultRegistry()
    with r.armed("serving.conn_drop"):
        assert r.is_armed("serving.conn_drop")
        assert r.fire("serving.conn_drop")
    assert not r.is_armed("serving.conn_drop")
    assert not r.fire("serving.conn_drop")


def test_armed_disarms_on_exception():
    r = FaultRegistry()
    with pytest.raises(KeyError):
        with r.armed("serving.conn_drop"):
            raise KeyError("boom")
    assert not r.is_armed("serving.conn_drop")


def test_reset_clears_specs_and_counters():
    r = FaultRegistry()
    r.enable("feed.stall")
    r.fire("feed.stall")
    r.reset()
    assert not r.is_armed("feed.stall")
    assert r.hits("feed.stall") == 0
    assert r.snapshot() == {}


def test_configure_from_dict_with_string_exception():
    r = FaultRegistry()
    r.configure({"checkpoint.write_fail": {"times": 1, "exc": "OSError"}})
    with pytest.raises(OSError):
        r.raise_if("checkpoint.write_fail")


def test_configure_rejects_non_exception_name():
    r = FaultRegistry()
    with pytest.raises(ValueError, match="not an .*exception"):
        r.configure({"feed.stall": {"exc": "print"}})


def test_configure_none_is_noop():
    r = FaultRegistry()
    r.configure(None)
    r.configure({})
    assert not r.is_armed("feed.stall")


def test_enable_validates_times_and_prob():
    r = FaultRegistry()
    with pytest.raises(ValueError, match="times"):
        r.enable("feed.stall", times=0)
    with pytest.raises(ValueError, match="prob"):
        r.enable("feed.stall", prob=0.0)
    with pytest.raises(ValueError, match="prob"):
        r.enable("feed.stall", prob=1.5)


def test_snapshot_reports_hits_and_fired():
    r = FaultRegistry()
    r.enable("serving.queue_reject", times=1)
    r.fire("serving.queue_reject")
    r.fire("serving.queue_reject")
    r.fire("serving.conn_drop")
    snap = r.snapshot()
    assert snap["serving.queue_reject"] == {"hits": 2, "fired": 1}
    assert snap["serving.conn_drop"] == {"hits": 1, "fired": 0}


def test_thread_safety_times_never_oversubscribed():
    """N threads hammering an armed point must fire EXACTLY ``times``
    faults in total — the charge decrement is atomic under the lock."""
    r = FaultRegistry()
    r.enable("serving.queue_reject", times=50)
    fired = []
    barrier = threading.Barrier(8)

    def worker():
        barrier.wait()
        count = sum(r.fire("serving.queue_reject") for _ in range(100))
        fired.append(count)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert sum(fired) == 50
    assert r.hits("serving.queue_reject") == 800


def test_config_wiring_arms_global_registry():
    """ZooConfig.faults arms the process-global registry at context init
    (the "via config" half of the per-test-or-via-config contract)."""
    from analytics_zoo_tpu.core import (ZooConfig, init_orca_context,
                                        stop_orca_context)
    stop_orca_context()
    cfg = ZooConfig(faults={"serving.queue_reject": {"times": 1}})
    init_orca_context("local", config=cfg)
    try:
        assert get_registry().is_armed("serving.queue_reject")
        assert get_registry().fire("serving.queue_reject")
    finally:
        stop_orca_context()


def test_feed_stall_point_is_wired():
    """DataFeed.epoch hits ``feed.stall`` once per step."""
    import numpy as np
    from analytics_zoo_tpu.core import init_orca_context
    from analytics_zoo_tpu.data import DataFeed
    init_orca_context("local")
    feed = DataFeed.from_arrays(np.zeros((8, 2), np.float32),
                                np.zeros((8, 1), np.float32),
                                batch_size=4, shuffle=False)
    from analytics_zoo_tpu.core import get_mesh
    before = get_registry().hits("feed.stall")
    list(feed.epoch(get_mesh(), 0))
    assert get_registry().hits("feed.stall") - before == 2


def test_training_fault_points_are_known():
    # PR 2 (gang supervision + self-healing) injection points
    for name in ("worker.crash", "worker.hang", "feed.read_fail",
                 "step.nan"):
        assert name in KNOWN_POINTS


def test_after_skips_initial_hits():
    """``after=K`` arms "fire on hit K+1": the deterministic handle for
    "crash at step N" in gang tests."""
    r = FaultRegistry()
    r.enable("step.nan", times=1, after=3)
    fires = [r.fire("step.nan") for _ in range(6)]
    assert fires == [False, False, False, True, False, False]
    assert r.hits("step.nan") == 6
    assert r.fired("step.nan") == 1


def test_after_validates_non_negative():
    r = FaultRegistry()
    with pytest.raises(ValueError, match="after"):
        r.enable("step.nan", after=-1)


def test_armed_points_lists_and_clears():
    r = FaultRegistry()
    assert r.armed_points() == []
    r.enable("feed.stall")
    r.enable("step.nan", times=1)
    assert r.armed_points() == ["feed.stall", "step.nan"]
    r.fire("step.nan")  # last charge consumed: auto-disarmed
    assert r.armed_points() == ["feed.stall"]
    r.reset()
    assert r.armed_points() == []


def test_feed_read_fail_point_is_wired_and_retried():
    """StreamingDataFeed hits ``feed.read_fail`` inside its retry loop: an
    armed one-shot failure is absorbed by retries=1 and every row still
    arrives exactly once."""
    import numpy as np
    from analytics_zoo_tpu.core import get_mesh, init_orca_context
    from analytics_zoo_tpu.data import StreamingDataFeed
    init_orca_context("local")
    feed = StreamingDataFeed(
        num_samples=8,
        load_sample=lambda i, rng=None: {"x": np.full((2,), float(i),
                                                      np.float32)},
        batch_size=4, shuffle=False, num_workers=1, retries=1)
    with get_registry().armed("feed.read_fail", times=1):
        batches = list(feed.epoch(get_mesh(), 0))
    assert get_registry().fired("feed.read_fail") == 1
    assert feed.load_failures == 1
    assert feed.skipped_rows == 0  # retried, not skipped
    rows = sorted(float(v) for b in batches
                  for v in np.asarray(b["x"])[:, 0])
    assert rows == [float(i) for i in range(8)]


def test_step_nan_point_is_wired():
    """The estimator hits ``step.nan`` once per train step; disarmed it
    must be a pure counter."""
    import numpy as np
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.core import init_orca_context
    from analytics_zoo_tpu.orca.learn import Estimator
    init_orca_context("local")
    est = Estimator.from_keras(nn.Sequential([nn.Dense(1)]), loss="mse",
                               learning_rate=1e-3)
    rng = np.random.default_rng(0)
    before = get_registry().hits("step.nan")
    est.fit((rng.normal(size=(64, 4)).astype(np.float32),
             rng.normal(size=(64, 1)).astype(np.float32)),
            epochs=1, batch_size=32, verbose=False)
    assert get_registry().hits("step.nan") - before == 2  # 2 steps
    assert get_registry().fired("step.nan") == 0
