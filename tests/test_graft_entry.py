"""Driver-contract tests: the __graft_entry__ surface the harness invokes.

The driver compile-checks ``entry()`` single-chip and runs
``dryrun_multichip(8)`` bare; these tests keep both paths green in CI
(the bare-subprocess re-exec path is additionally exercised by invoking
the module exactly as the driver does)."""

import os
import subprocess
import sys

import jax
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def test_entry_compiles_and_runs():
    import __graft_entry__ as g
    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (4, 2)
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.parametrize("n", [4, 8])
def test_dryrun_local_parallel_modes(n):
    # conftest provides 8 CPU devices; exercises dp/tp/sp/pp/ep/fsdp math
    # at two device counts in-process
    import __graft_entry__ as g
    g._dryrun_local(n)


def test_dryrun_bare_subprocess_self_provisions():
    """The driver's exact invocation: bare process, no test env."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("XLA_", "JAX_"))}
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"  # keep CI off the real chip
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8)"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    assert "all parallel modes ok" in proc.stdout
