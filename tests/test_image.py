"""ImageSet + streaming input pipeline (VERDICT r1 missing #4/weak #6).

Covers: transform chain correctness, directory reading, the streaming
feed's equivalence with the in-RAM feed, backpressure-bounded prefetch,
error propagation, and a toy ResNet train from real JPEG files.
"""

import numpy as np
import pytest

from analytics_zoo_tpu.core import init_orca_context, get_mesh
from analytics_zoo_tpu.data import (DataFeed, ImageCenterCrop, ImageNormalize,
                                    ImageRandomCrop, ImageRandomFlip,
                                    ImageResize, ImageSet, StreamingDataFeed)


def _write_dataset(root, n_per_class=8, size=48, classes=("cat", "dog")):
    from PIL import Image
    rng = np.random.default_rng(0)
    for c in classes:
        d = root / c
        d.mkdir(parents=True)
        for i in range(n_per_class):
            arr = rng.integers(0, 255, (size, size, 3), dtype=np.uint8)
            Image.fromarray(arr).save(d / f"{c}_{i}.jpg")
    return str(root)


# -- transforms ---------------------------------------------------------------

def test_transform_chain():
    img = np.arange(40 * 40 * 3, dtype=np.uint8).reshape(40, 40, 3)
    out = ImageResize(32, 32)(img)
    assert out.shape == (32, 32, 3)
    out = ImageCenterCrop(16, 16)(out)
    assert out.shape == (16, 16, 3)
    norm = ImageNormalize(mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5))(out)
    assert norm.dtype == np.float32
    assert np.all(norm >= -1.001) and np.all(norm <= 1.001)
    rng = np.random.default_rng(0)
    flipped = ImageRandomFlip(p=1.0)(out, rng=rng)
    np.testing.assert_array_equal(flipped, out[:, ::-1])
    crop = ImageRandomCrop(8, 8)(out, rng=rng)
    assert crop.shape == (8, 8, 3)


def test_imageset_read(tmp_path):
    root = _write_dataset(tmp_path / "imgs")
    iset = ImageSet.read(root, with_label=True)
    assert len(iset) == 16
    assert iset.class_names == ["cat", "dog"]
    assert sorted(set(iset.labels.tolist())) == [0, 1]
    sample = iset.transform(ImageResize(32, 32),
                            ImageNormalize()).load_sample(0)
    assert sample["x"].shape == (32, 32, 3)
    assert sample["x"].dtype == np.float32
    assert sample["y"] in (0, 1)


# -- streaming feed -----------------------------------------------------------

def test_streaming_feed_matches_in_ram_feed(tmp_path):
    """Deterministic config (1 worker, no shuffle) must reproduce the plain
    DataFeed batches bit-for-bit."""
    root = _write_dataset(tmp_path / "imgs")
    init_orca_context("local")
    mesh = get_mesh()
    iset = ImageSet.read(root).transform(ImageResize(16, 16),
                                         ImageNormalize())
    stream = iset.to_feed(batch_size=8, shuffle=False, num_workers=1)
    shards = iset.to_shards(num_shards=2)
    plain = DataFeed.from_shards(shards, batch_size=8, shuffle=False)
    got = [{k: np.asarray(v) for k, v in b.items()}
           for b in stream.epoch(mesh, 0)]
    want = [{k: np.asarray(v) for k, v in b.items()}
            for b in plain.epoch(mesh, 0)]
    assert len(got) == len(want) == 2
    for g, w in zip(got, want):
        np.testing.assert_allclose(g["x"], w["x"], rtol=1e-6)
        np.testing.assert_array_equal(g["y"], w["y"])


def test_streaming_feed_with_readahead_matches_direct_reads(tmp_path):
    """The per-worker FileReadahead path (io overlapped with decode) must
    decode bit-identical batches to the direct-read path, and the feed
    must surface the loader's io-wait through ``feed.io_wait_ms``."""
    from analytics_zoo_tpu.core import metrics
    root = _write_dataset(tmp_path / "imgs")
    init_orca_context("local")
    mesh = get_mesh()
    iset = ImageSet.read(root).transform(ImageResize(16, 16),
                                         ImageNormalize())
    direct = iset.to_feed(batch_size=8, shuffle=False, num_workers=1)
    got_direct = [np.asarray(b["x"]) for b in direct.epoch(mesh, 0)]
    metrics.get_registry().reset()
    ahead = iset.to_feed(batch_size=8, shuffle=False, num_workers=1,
                         readahead=4)
    got_ahead = [np.asarray(b["x"]) for b in ahead.epoch(mesh, 0)]
    for a, b in zip(got_direct, got_ahead):
        np.testing.assert_array_equal(a, b)
    assert iset.readahead == 0  # to_feed(readahead=) must not mutate iset


def test_streaming_feed_multiworker_covers_epoch(tmp_path):
    root = _write_dataset(tmp_path / "imgs")
    init_orca_context("local")
    mesh = get_mesh()
    iset = ImageSet.read(root).transform(ImageResize(16, 16),
                                         ImageNormalize())
    stream = iset.to_feed(batch_size=8, shuffle=True, num_workers=3,
                          prefetch_batches=2)
    ys = []
    for b in stream.epoch(mesh, 0):
        assert np.asarray(b["x"]).shape == (8, 16, 16, 3)
        ys.extend(np.asarray(b["y"]).tolist())
    assert len(ys) == 16       # both batches, every row exactly once
    assert sorted(ys) == [0] * 8 + [1] * 8


def test_streaming_feed_propagates_loader_error():
    init_orca_context("local")
    mesh = get_mesh()

    def bad_loader(i, rng=None):
        if i == 3:
            raise ValueError("corrupt sample")
        return {"x": np.zeros((4,), np.float32)}

    feed = StreamingDataFeed(num_samples=16, load_sample=bad_loader,
                             batch_size=8, shuffle=False, num_workers=2)
    with pytest.raises(ValueError, match="corrupt sample"):
        list(feed.epoch(mesh, 0))


def test_streaming_feed_trains_resnet(tmp_path):
    """VERDICT r1 'done' criterion: a toy-scale ResNet trained from JPEG
    files through the streaming pipeline + estimator."""
    from analytics_zoo_tpu.models import ResNet
    from analytics_zoo_tpu.orca.learn import Estimator
    root = _write_dataset(tmp_path / "imgs", n_per_class=8, size=40)
    init_orca_context("local")
    iset = ImageSet.read(root).transform(
        ImageResize(36, 36), ImageRandomCrop(32, 32), ImageRandomFlip(),
        ImageNormalize())
    feed = iset.to_feed(batch_size=8, shuffle=True, num_workers=2)
    model = ResNet(depth=50, class_num=2)
    est = Estimator.from_keras(model, loss="sparse_categorical_crossentropy",
                               learning_rate=1e-3)
    hist = est.fit(feed, epochs=2, batch_size=8, verbose=False)
    assert len(hist["loss"]) == 2
    assert all(np.isfinite(v) for v in hist["loss"])
    # predict path goes through the plain feed
    sample = np.stack([iset.load_sample(i)["x"] for i in range(8)])
    preds = est.predict(sample, batch_size=8)
    assert preds.shape == (8, 2)


def test_predict_on_streaming_feed_covers_all_rows(tmp_path):
    """predict must return one row per input even when the feed drops the
    epoch remainder (regression: silent row loss)."""
    from analytics_zoo_tpu.orca.learn import Estimator
    import analytics_zoo_tpu.nn as nn
    init_orca_context("local")

    def loader(i, rng=None):
        return {"x": np.full((4,), float(i), np.float32),
                "y": np.int32(i % 2)}

    feed = StreamingDataFeed(num_samples=20, load_sample=loader,
                             batch_size=8, shuffle=False, num_workers=2)

    class M(nn.Module):
        def forward(self, scope, x):
            return scope.child(nn.Dense(2), x, name="fc")

    est = Estimator.from_keras(M(), loss="sparse_categorical_crossentropy",
                               learning_rate=1e-2)
    est.fit(feed, epochs=1, batch_size=8, verbose=False)
    preds = est.predict(feed, batch_size=8)
    assert preds.shape == (20, 2)   # 2 full batches + 4-row remainder
    # row ALIGNMENT must hold under multi-worker decode (regression: batches
    # used to arrive in completion order, silently permuting predictions)
    direct = est.predict(
        np.stack([loader(i)["x"] for i in range(20)]), batch_size=8)
    np.testing.assert_allclose(preds, direct, rtol=1e-5)
    shuffled = StreamingDataFeed(num_samples=20, load_sample=loader,
                                 batch_size=8, shuffle=True)
    with pytest.raises(ValueError, match="shuffle=False"):
        est.predict(shuffled, batch_size=8)


def test_color_jitter_transforms():
    from analytics_zoo_tpu.data import (ImageBrightness, ImageColorJitter,
                                        ImageContrast, ImageSaturation)
    rng = np.random.default_rng(0)
    img = rng.integers(40, 200, (16, 16, 3)).astype(np.uint8)
    for t in (ImageBrightness(32), ImageContrast(), ImageSaturation(),
              ImageColorJitter()):
        out = t(img, rng=np.random.default_rng(1))
        assert out.shape == img.shape and out.dtype == np.uint8
        # deterministic under the same rng (streaming-feed reproducibility)
        out2 = t(img, rng=np.random.default_rng(1))
        np.testing.assert_array_equal(out, out2)
    # fixed-range value checks (no dependence on what the rng draws):
    # contrast 2x about the mean
    con = ImageContrast(2.0, 2.0)(img, rng=np.random.default_rng(2))
    f = img.astype(np.float32)
    want = np.clip((f - f.mean((0, 1), keepdims=True)) * 2.0
                   + f.mean((0, 1), keepdims=True), 0, 255).astype(np.uint8)
    np.testing.assert_array_equal(con, want)
    # gray image is a fixed point of saturation
    gray = np.full((8, 8, 3), 100, np.uint8)
    sat = ImageSaturation(0.2, 0.2)(gray, rng=np.random.default_rng(3))
    np.testing.assert_allclose(sat, gray, atol=1)
    # jitter with wide ranges changes a varied image
    jit = ImageColorJitter(brightness=50, contrast=(1.9, 2.0),
                           saturation=(1.9, 2.0))(
        img, rng=np.random.default_rng(4))
    assert not np.array_equal(jit, img)


def test_resnet_space_to_depth_stem_matches_conv():
    """stem='space_to_depth' is numerically identical to the plain
    7x7/s2 SAME conv stem, with an interchangeable param tree."""
    import jax
    from analytics_zoo_tpu.models import ResNet
    rng = np.random.default_rng(7)
    x = rng.normal(size=(2, 32, 32, 3)).astype(np.float32)
    conv_net = ResNet(depth=18, class_num=5, width=8)
    s2d_net = ResNet(depth=18, class_num=5, width=8, stem="space_to_depth")
    variables = conv_net.init(jax.random.PRNGKey(0), x)
    want, _ = conv_net.apply(variables, x, training=False)
    got, _ = s2d_net.apply(variables, x, training=False)  # same params
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-4, rtol=2e-4)
