"""Worker for the SIGKILL-mid-async-save chaos storm (tests/test_chaos.py).

Trains a sharded-embedding NeuralCF with ``checkpoint_async=True`` and a
trigger every 2 steps, so async generations (full + deltas) stream into
``model_dir`` while the parent test kills the process with SIGKILL at
seeded offsets.  IMMEDIATELY BEFORE each async save the worker writes a
plain synchronous mirror of the exact same train state into
``mirror_dir/step_<n>`` — the oracle the test compares the post-kill
restore against, row-exactly.  The mirror lands (synchronously, before
``save_async`` even enqueues) strictly earlier than its generation's
manifest line can, so every VISIBLE generation has a complete mirror no
matter where the kill hit.

Markers on stdout: ``TRAINING_STARTED``, then ``TRIGGERED step=<n>``
after each trigger firing (printed only once the async snapshot was
accepted).
"""

import os
import sys

import numpy as np


def main() -> None:
    model_dir = sys.argv[1]
    mirror_dir = sys.argv[2]
    epochs = int(sys.argv[3]) if len(sys.argv) > 3 else 100000
    import jax
    jax.config.update("jax_platforms", "cpu")

    from analytics_zoo_tpu.core import checkpoint as ckpt_io
    from analytics_zoo_tpu.core import init_orca_context
    from analytics_zoo_tpu.models import NeuralCF
    from analytics_zoo_tpu.orca.learn import Estimator
    from analytics_zoo_tpu.orca.learn.trigger import SeveralIteration

    init_orca_context("local")
    model = NeuralCF(user_count=64, item_count=40, class_num=2,
                     user_embed=8, item_embed=8, hidden_layers=(16, 8),
                     mf_embed=8, sharded_embeddings=True)
    est = Estimator.from_keras(
        model, loss="sparse_categorical_crossentropy", optimizer="adam",
        learning_rate=1e-2, seed=7, model_dir=model_dir,
        checkpoint_async=True, checkpoint_inflight="block",
        checkpoint_keep_last=3)
    rng = np.random.default_rng(0)
    x = np.stack([rng.integers(0, 64, 512),
                  rng.integers(0, 40, 512)], 1).astype(np.int32)
    y = (rng.random(512) < 0.5).astype(np.int32)

    orig_trigger = est._trigger_save

    def mirrored_trigger() -> None:
        step = est._py_step
        tree = jax.device_get(est._save_tree())
        ckpt_io.save(os.path.join(mirror_dir, f"step_{step}"), tree,
                     step=step, extra={"epoch": int(est._epoch)})
        orig_trigger()
        print(f"TRIGGERED step={step}", flush=True)

    est._trigger_save = mirrored_trigger

    print("TRAINING_STARTED", flush=True)
    est.fit((x, y), epochs=epochs, batch_size=64,
            checkpoint_trigger=SeveralIteration(2), verbose=False)
    print(f"FINISHED step={est._py_step}", flush=True)


if __name__ == "__main__":
    main()
