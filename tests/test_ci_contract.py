"""CI contract: optional-dependency suites must be VISIBLE (VERDICT r2
weak #6 — a silently-skipped import suite shrinks coverage without
failing anything).

This image is expected to carry torch, tensorflow, PIL and pandas; the
differential-import and image suites depend on them via importorskip, so
if one disappears those suites silently vanish.  This test fails loudly
instead, and documents which optional suites ran.
"""

import importlib

import pytest

# (module, suites that silently skip without it)
_EXPECTED = [
    ("torch", ["tests/test_net.py (torch half)", "tests/test_interop.py"]),
    ("tensorflow", ["tests/test_net.py (tf half)",
                    "tests/test_layers_zoo.py goldens"]),
    ("PIL", ["tests/test_image.py"]),
    ("pandas", ["tests/test_chronos.py", "tests/test_friesian.py",
                "tests/test_nnframes.py"]),
]


@pytest.mark.parametrize("module,suites", _EXPECTED,
                         ids=[m for m, _ in _EXPECTED])
def test_optional_suite_dependency_present(module, suites):
    try:
        importlib.import_module(module)
    except ImportError as e:
        pytest.fail(
            f"optional dependency {module!r} is missing — the following "
            f"suites are silently skipping: {suites} ({e})")


def test_statsmodels_absence_is_covered_by_numpy_arima():
    """statsmodels is legitimately absent in this image; the ARIMA path
    must still execute via the numpy backend (not skip)."""
    from analytics_zoo_tpu.chronos.forecaster import ARIMAForecaster
    f = ARIMAForecaster(order=(1, 0, 0))
    assert f.backend in ("numpy", "statsmodels")


def test_keras1_layer_inventory_complete():
    """Every keras-1 layer name the reference exposed (PARITY.md §2.3a)
    resolves in analytics_zoo_tpu.nn — implemented or aliased.  A name
    silently vanishing from the namespace fails CI, keeping the audit
    honest."""
    import analytics_zoo_tpu.nn as nn
    names = """Dense Activation Dropout Flatten Reshape Permute RepeatVector
    Masking Merge Highway MaxoutDense SpatialDropout1D SpatialDropout2D
    SpatialDropout3D GaussianDropout GaussianNoise ActivityRegularization
    TimeDistributed Bidirectional Embedding WordEmbedding SparseEmbedding
    Convolution1D Convolution2D Convolution3D AtrousConvolution1D
    AtrousConvolution2D Deconvolution2D SeparableConvolution2D
    LocallyConnected1D LocallyConnected2D ShareConvolution2D
    Cropping1D Cropping2D Cropping3D UpSampling1D UpSampling2D UpSampling3D
    ZeroPadding1D ZeroPadding2D ZeroPadding3D
    MaxPooling1D MaxPooling2D MaxPooling3D AveragePooling1D AveragePooling2D
    AveragePooling3D GlobalMaxPooling1D GlobalMaxPooling2D GlobalMaxPooling3D
    GlobalAveragePooling1D GlobalAveragePooling2D GlobalAveragePooling3D
    SimpleRNN LSTM GRU ConvLSTM2D ConvLSTM3D BatchNormalization
    LeakyReLU PReLU ELU ThresholdedReLU SReLU
    AddConstant MulConstant LRN2D Select Narrow Squeeze Exp Log Power Scale
    Sqrt Square Identity Negative HardShrink SoftShrink HardTanh Threshold
    GaussianSampler ResizeBilinear CAdd CMul Lambda Input
    TransformerLayer merge""".split()
    missing = [n for n in names if not hasattr(nn, n)]
    assert not missing, f"keras-1 inventory regressed: {missing}"
    assert len(names) == 90
