"""CI contract: optional-dependency suites must be VISIBLE (VERDICT r2
weak #6 — a silently-skipped import suite shrinks coverage without
failing anything).

This image is expected to carry torch, tensorflow, PIL and pandas; the
differential-import and image suites depend on them via importorskip, so
if one disappears those suites silently vanish.  This test fails loudly
instead, and documents which optional suites ran.
"""

import importlib

import pytest

# (module, suites that silently skip without it)
_EXPECTED = [
    ("torch", ["tests/test_net.py (torch half)", "tests/test_interop.py"]),
    ("tensorflow", ["tests/test_net.py (tf half)",
                    "tests/test_layers_zoo.py goldens"]),
    ("PIL", ["tests/test_image.py"]),
    ("pandas", ["tests/test_chronos.py", "tests/test_friesian.py",
                "tests/test_nnframes.py"]),
]


@pytest.mark.parametrize("module,suites", _EXPECTED,
                         ids=[m for m, _ in _EXPECTED])
def test_optional_suite_dependency_present(module, suites):
    try:
        importlib.import_module(module)
    except ImportError as e:
        pytest.fail(
            f"optional dependency {module!r} is missing — the following "
            f"suites are silently skipping: {suites} ({e})")


def test_statsmodels_absence_is_covered_by_numpy_arima():
    """statsmodels is legitimately absent in this image; the ARIMA path
    must still execute via the numpy backend (not skip)."""
    from analytics_zoo_tpu.chronos.forecaster import ARIMAForecaster
    f = ARIMAForecaster(order=(1, 0, 0))
    assert f.backend in ("numpy", "statsmodels")
